"""Serving stack: compile-once autoregressive decode with the fused
transformer (fixed-shape KV cache) — optionally weight-only int8.

The reference's `FusedMultiTransformer` serving path
(`incubate/nn/layer/fused_transformer.py:1016`, int8 :1464) — here the
whole decode loop is ONE lax.scan executable; `quant_bits=8` stores
int8 weights + per-channel scales and dequantizes inside the bf16
matmul (`weight_only_linear_kernel.h` capability).
"""
import time

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPTForGeneration


def main(quant_bits=0, batch=4, max_new=64):
    paddle.seed(0)
    net = GPTForGeneration(vocab_size=5000, hidden_size=256,
                           num_layers=4, num_attention_heads=8,
                           max_position_embeddings=256,
                           weight_only=(quant_bits == 8))
    net.eval()
    prompt = paddle.to_tensor(
        np.random.RandomState(0).randint(0, 5000, (batch, 16)))
    t0 = time.perf_counter()
    out, _ = net.generate(prompt, max_new_tokens=max_new)
    first = time.perf_counter() - t0   # includes compile
    t0 = time.perf_counter()
    out, _ = net.generate(prompt, max_new_tokens=max_new)
    steady = time.perf_counter() - t0
    toks = batch * max_new
    print(f"quant_bits={quant_bits}: first call {first:.1f}s "
          f"(compile), steady {steady * 1e3:.0f} ms "
          f"({toks / steady:,.0f} tok/s), out shape {out.shape}")
    return out


def main_speculative(batch=1, max_new=64, draft_k=4):
    """Speculative decoding demo: n-gram prompt-lookup drafts + one
    compiled verify step (greedy, token-identical to plain decode).
    Repetitive prompts are the favourable regime — each accepted draft
    token skips one whole latency-bound decode step."""
    paddle.seed(0)
    net = GPTForGeneration(vocab_size=5000, hidden_size=256,
                           num_layers=4, num_attention_heads=8,
                           max_position_embeddings=256)
    net.eval()
    prompt = paddle.to_tensor(
        np.tile(np.arange(10, 26, dtype=np.int32), (batch, 2)))
    base, _ = net.generate(prompt, max_new_tokens=max_new)
    for _ in range(2):  # compile, then steady
        t0 = time.perf_counter()
        out, _ = net.generate(prompt, max_new_tokens=max_new,
                              draft_k=draft_k)
        dt = time.perf_counter() - t0
    steps = len(net.last_accept_counts)
    assert out.numpy().tolist() == base.numpy().tolist()
    print(f"speculative draft_k={draft_k}: {batch * max_new} tokens in "
          f"{steps} verify steps ({batch * max_new / dt:,.0f} tok/s), "
          "token-identical to plain greedy")
    return out


def main_kv_int8(n_req=8, max_new=16):
    """Int8 quantized KV block pools (PR 9): same continuous-batching
    engine, pools stored int8 with per-entry-per-head fp32 scales —
    ~2.7-3.8x the resident tokens per chip at a bounded greedy
    divergence (docs/SERVING.md "KV quantization")."""
    from paddle_tpu.serving.engine import ServingEngine
    paddle.seed(0)
    net = GPTForGeneration(vocab_size=5000, hidden_size=256,
                           num_layers=4, num_attention_heads=8,
                           max_position_embeddings=256)
    net.eval()
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 5000, int(n)).tolist()
               for n in rng.randint(8, 48, n_req)]
    outs = {}
    for dt in (None, "int8"):
        eng = ServingEngine(net, max_slots=4, block_size=16,
                            max_seq_len=128, cache_dtype="float32",
                            kv_dtype=dt, seed=0)
        outs[dt] = eng.generate_batch(prompts, max_new_tokens=max_new)
        print(f"kv_dtype={dt or 'float32'}: "
              f"{eng.kv.kv_bytes_per_token} KV bytes/token, "
              f"{eng.kv.allocator.capacity} blocks")
    total = sum(len(o) for o in outs[None])
    agree = sum(a == b for x, y in zip(outs[None], outs["int8"])
                for a, b in zip(x, y))
    print(f"int8 greedy agreement: {agree}/{total} tokens")
    return outs["int8"]


def main_async_frontend(n_users=6, max_new=24):
    """Multi-tenant async serving demo: every "user" sends the same
    system prompt plus their own short question through the asyncio
    `ServingFrontend`. The radix prefix cache serves the shared head
    from cached KV blocks (only the first wave prefills it), tokens
    stream back per step, and one user cancels mid-stream — slot, KV
    blocks and prefix locks come back without disturbing the rest."""
    import asyncio

    from paddle_tpu.serving.engine import ServingEngine
    from paddle_tpu.serving.frontend import ServingFrontend

    paddle.seed(0)
    net = GPTForGeneration(vocab_size=5000, hidden_size=256,
                           num_layers=4, num_attention_heads=8,
                           max_position_embeddings=256)
    net.eval()
    rng = np.random.RandomState(0)
    system_prompt = rng.randint(1, 5000, 32).tolist()
    questions = [rng.randint(1, 5000, 6).tolist()
                 for _ in range(n_users)]

    async def user(fe, i):
        toks = []
        async for t in fe.stream(system_prompt + questions[i],
                                 max_new_tokens=max_new,
                                 tenant=f"user{i % 3}"):
            toks.append(t)
            if i == 0 and len(toks) == 4:
                break            # user 0 hangs up mid-generation
        return toks

    async def serve():
        engine = ServingEngine(net, max_slots=2, block_size=16,
                               max_seq_len=128, prefix_caching=True)
        t0 = time.perf_counter()
        async with ServingFrontend(engine, max_pending=16) as fe:
            outs = await asyncio.gather(
                *[user(fe, i) for i in range(n_users)])
        dt = time.perf_counter() - t0
        pc = engine.prefix_cache
        toks = sum(len(o) for o in outs)
        print(f"async frontend: {n_users} users x shared 32-token "
              f"system prompt -> {toks} tokens in {dt:.1f}s "
              f"(incl. compile); prefix hit ratio "
              f"{pc.hit_ratio():.2f} ({pc.hit_tokens} cached / "
              f"{pc.miss_tokens} prefilled tokens), user0 cancelled "
              f"after {len(outs[0])} tokens")
        return outs

    return asyncio.run(serve())


def main_router(n_users=8, max_new=16):
    """Distributed serving demo (ISSUE 8): TWO replica engines behind a
    prefix-affinity `ReplicaRouter`. Every user shares one system
    prompt, so affinity dispatch concentrates them on the replica that
    already caches its KV — watch the affinity hits and the per-replica
    prefix hit ratios (the idle replica stays cold instead of paying a
    duplicate prefill of the shared head)."""
    import asyncio

    from paddle_tpu.serving.distributed import ReplicaRouter
    from paddle_tpu.serving.engine import ServingEngine
    from paddle_tpu.serving.frontend import ServingFrontend

    paddle.seed(0)
    net = GPTForGeneration(vocab_size=5000, hidden_size=256,
                           num_layers=4, num_attention_heads=8,
                           max_position_embeddings=256)
    net.eval()
    rng = np.random.RandomState(0)
    system_prompt = rng.randint(1, 5000, 32).tolist()
    questions = [rng.randint(1, 5000, 6).tolist()
                 for _ in range(n_users)]

    async def serve():
        fes = []
        for _ in range(2):
            eng = ServingEngine(net, max_slots=2, block_size=16,
                                max_seq_len=128, prefix_caching=True)
            eng.generate_batch([[7, 7]], max_new_tokens=1)  # warm
            fes.append(ServingFrontend(eng, max_pending=16))
        router = ReplicaRouter(fes)
        t0 = time.perf_counter()
        async with router:
            outs = []
            for q in questions:        # staggered arrivals
                outs.append(await router.submit(
                    system_prompt + q, max_new_tokens=max_new))
        dt = time.perf_counter() - t0
        stats = router.stats()
        hits = [fe.engine.prefix_cache.hit_tokens for fe in fes]
        print(f"router: {n_users} users x shared system prompt over 2 "
              f"replicas -> {sum(len(o) for o in outs)} tokens in "
              f"{dt:.1f}s; affinity hits "
              f"{stats['affinity_hits']}/{stats['dispatches']}, "
              f"per-replica cached-prefix tokens {hits}")
        return outs

    return asyncio.run(serve())


def main_multi_lora(n_req=12, max_new=12):
    """Multi-tenant LoRA demo (ISSUE 14): three customer finetunes +
    base-model traffic through ONE engine with only TWO usable
    adapter slots, so a cold tenant's arrival mid-stream EVICTS the
    LRU idle adapter and reloads it later — all under a single
    compiled mixed step (the fixed slot tensors never change shape).
    Prints the slot-cache churn and the marginal HBM per tenant."""
    from paddle_tpu.serving.adapters import make_random_adapter
    from paddle_tpu.serving.engine import ServingEngine
    paddle.seed(0)
    net = GPTForGeneration(vocab_size=5000, hidden_size=256,
                           num_layers=4, num_attention_heads=8,
                           max_position_embeddings=256)
    net.eval()
    rng = np.random.RandomState(0)
    eng = ServingEngine(net, max_slots=4, block_size=16,
                        max_seq_len=128, cache_dtype="float32",
                        seed=0, max_adapters=3, lora_rank=8)
    tenants = ("acme", "globex", "initech")
    for i, t in enumerate(tenants):
        eng.register_adapter(t, make_random_adapter(
            net.decoder, 8, seed=i + 1, scale=0.05))
    # phase 1: acme + globex traffic fills both usable slots
    mix = [None, "acme", "globex", "acme", "globex", "acme"]
    reqs = [eng.submit(rng.randint(1, 5000, 12).tolist(), max_new,
                       adapter_id=t) for t in mix]
    eng.run()
    print(f"phase 1 (acme+globex+base): hits={eng.adapters.cache_hits} "
          f"misses={eng.adapters.cache_misses} "
          f"evictions={eng.adapters.evictions}")
    # phase 2: initech arrives MID-STREAM — one idle adapter is
    # LRU-evicted, its slot rewritten by the one jitted slot-write
    late = [eng.submit(rng.randint(1, 5000, 12).tolist(), max_new,
                       adapter_id=t)
            for t in ("initech", "acme", "initech")]
    eng.run()
    reqs += late
    done = sum(r.state == "finished" for r in reqs)
    print(f"phase 2 (+initech mid-stream): "
          f"evictions={eng.adapters.evictions} "
          f"hit_ratio={eng.adapters.hit_ratio():.2f}; "
          f"{done}/{len(reqs)} requests finished, "
          f"{eng.adapters.bytes_per_slot // 1024} KiB marginal "
          f"HBM/tenant (vs a full model copy per tenant)")
    return reqs


if __name__ == "__main__":
    main(quant_bits=0)
    main(quant_bits=8)
    main_speculative()
    main_kv_int8()
    main_async_frontend()
    main_router()
    main_multi_lora()
