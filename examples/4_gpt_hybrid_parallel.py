"""BASELINE config 4: GPT with Fleet-style hybrid parallelism — dp +
sharding(ZeRO) + pp (+ mp + sequence parallel), all inside one compiled
step. Sizes default small so it runs on any mesh; pass --full for the
1.3B configuration (needs a v5e-8-class mesh).
"""
import argparse

import numpy as np
import jax

from paddle_tpu.parallel.hybrid_gpt import GPTConfig, HybridGPT


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--pp", type=int, default=2)
    ap.add_argument("--mp", type=int, default=2)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--full", action="store_true",
                    help="GPT-3 1.3B configuration")
    args = ap.parse_args()

    n_needed = args.dp * args.pp * args.mp
    if jax.device_count() < n_needed:
        raise SystemExit(f"need {n_needed} devices; jax sees "
                         f"{jax.device_count()} (use the CPU mesh: "
                         f"XLA_FLAGS=--xla_force_host_platform_device_count=8)")
    if args.full:
        cfg = GPTConfig(vocab_size=50304, seq_len=2048, d_model=2048,
                        n_heads=16, n_layers=24, dp=args.dp, pp=args.pp,
                        mp=args.mp, micro_batches=4,
                        sequence_parallel=True, zero_stage=2, remat=True)
        batch = 4 * args.dp * 4
    else:
        cfg = GPTConfig(vocab_size=512, seq_len=64, d_model=64, n_heads=4,
                        n_layers=4, dp=args.dp, pp=args.pp, mp=args.mp,
                        micro_batches=2, sequence_parallel=(args.mp > 1),
                        zero_stage=1, remat=True,
                        compute_dtype=jax.numpy.float32)
        batch = 4 * args.dp

    trainer = HybridGPT(cfg)
    params, opt = trainer.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    for step in range(args.steps):
        tok = rng.randint(0, cfg.vocab_size,
                          (batch, cfg.seq_len)).astype(np.int32)
        tok_d, lab_d = trainer.shard_data(tok, tok)
        params, opt, loss = trainer.train_step(params, opt, tok_d, lab_d,
                                               step_num=step + 1)
        print(f"step {step}: loss {float(jax.device_get(loss)):.4f} "
              f"(mesh dp={cfg.dp} pp={cfg.pp} mp={cfg.mp} "
              f"sp={cfg.sequence_parallel} zero={cfg.zero_stage})")


if __name__ == "__main__":
    main()
