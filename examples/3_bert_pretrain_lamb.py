"""BASELINE config 3: BERT pretraining objective (MLM+NSP) with LAMB."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models import (bert_base, bert_tiny, BertForPretraining,
                               BertPretrainingCriterion)


def synthetic_batch(rng, batch, seq, vocab):
    tok = rng.randint(1, vocab, (batch, seq))
    mlm = rng.randint(0, vocab, (batch, seq))
    mlm[rng.rand(batch, seq) > 0.15] = -1  # only 15% masked positions
    nsp = rng.randint(0, 2, (batch,))
    return tok, mlm, nsp


def main(steps=20, batch=8, seq=128, tiny=True):
    bert = bert_tiny() if tiny else bert_base()
    model = BertForPretraining(bert)
    crit = BertPretrainingCriterion(bert.vocab_size)
    opt = paddle.optimizer.Lamb(learning_rate=1e-3,
                                lamb_weight_decay=0.01,
                                parameters=model.parameters())
    rng = np.random.RandomState(0)
    model.train()
    for step in range(steps):
        tok, mlm, nsp = synthetic_batch(rng, batch, seq, bert.vocab_size)
        pred, rel = model(paddle.to_tensor(tok))
        loss = crit(pred, rel, paddle.to_tensor(mlm),
                    paddle.to_tensor(nsp))
        loss.backward()
        opt.step()
        opt.clear_grad()
        if step % 5 == 0:
            print(f"step {step}: loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
