"""BASELINE config 5: Wide&Deep on Criteo-style slot data with the native
parameter-server engine (C++ tables + DataFeed; AUC metric).

Single-process by default; set the PS env for true client/server mode:
  TRAINING_ROLE=PSERVER PADDLE_PSERVERS_IP_PORT_LIST=... (server)
  TRAINING_ROLE=TRAINER PADDLE_PSERVERS_IP_PORT_LIST=... (trainer)
"""
import os
import tempfile

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.ps import InMemoryDataset, SparseEmbedding
from paddle_tpu.ps.runtime import get_ps_runtime


def make_slot_files(path, n=20000, slots=(1, 2, 3, 4), vocab=10000,
                    zipf=None):
    """`zipf` (e.g. 1.3) skews the sign distribution the way real CTR
    traffic is skewed — the hot head is what the ps.heter hot-ID cache
    exists for; None keeps the original uniform draw."""
    rng = np.random.RandomState(0)
    with open(path, "w") as f:
        for _ in range(n):
            if zipf is not None:
                feats = [int(rng.zipf(zipf) % vocab) for _ in slots]
            else:
                feats = [rng.randint(0, vocab) for _ in slots]
            label = int((feats[0] % 3 == 0) ^ (feats[1] % 2 == 0))
            f.write(f"{label} " + " ".join(
                f"{s}:{s * 100000 + v}" for s, v in zip(slots, feats))
                + "\n")
    return path


def make_raw_logs(path, n=20000, n_slots=4, vocab=10000):
    """Raw click logs: `<click> <f1> <f2> <f3> <f4>` — NOT the slot
    format; the DataGenerator below parses them (fleet data_generator
    deployment mode)."""
    rng = np.random.RandomState(0)
    with open(path, "w") as f:
        for _ in range(n):
            feats = [rng.randint(0, vocab) for _ in range(n_slots)]
            label = int((feats[0] % 3 == 0) ^ (feats[1] % 2 == 0))
            f.write(f"{label} " + " ".join(map(str, feats)) + "\n")
    return path


class WideDeepGenerator:
    """User parser (fleet data_generator.py parity): raw log line ->
    [(slot_name, [sign...]), ...]."""

    def generate_sample(self, line):
        def local_iter():
            parts = line.split()
            label = int(parts[0])
            yield [("label", [label])] + [
                (f"slot{i+1}", [(i + 1) * 100000 + int(v)])
                for i, v in enumerate(parts[1:])]
        return local_iter


def main(epochs=3, batch_size=512, dim=8, use_data_generator=True):
    from paddle_tpu.ps.data_generator import MultiSlotDataGenerator
    tmp = tempfile.mkdtemp()
    slots = [1, 2, 3, 4]

    ds = InMemoryDataset()
    ds.init(batch_size=batch_size, slots=slots, max_per_slot=1)
    if use_data_generator:
        raw = make_raw_logs(os.path.join(tmp, "raw-0.txt"))

        class Gen(WideDeepGenerator, MultiSlotDataGenerator):
            pass

        gen = Gen()
        gen.set_slots([f"slot{i}" for i in slots])
        ds.load_from_generator(gen, [raw])
    else:
        data = make_slot_files(os.path.join(tmp, "part-0.txt"))
        ds.set_filelist([data])
        ds.load_into_memory()
    ds.global_shuffle(seed=42)
    print("records:", ds.get_memory_data_size())

    rt = get_ps_runtime()
    table = rt.create_sparse_table(0, dim=dim, sgd_rule="adagrad",
                                   learning_rate=0.1)
    emb = SparseEmbedding(dim=dim, table=table)
    deep = nn.Sequential(nn.Linear(len(slots) * dim, 64), nn.ReLU(),
                         nn.Linear(64, 32), nn.ReLU(), nn.Linear(32, 1))
    wide = nn.Linear(len(slots) * dim, 1)
    opt = paddle.optimizer.Adam(
        1e-3, parameters=deep.parameters() + wide.parameters())
    auc = paddle.metric.Auc()

    for epoch in range(epochs):
        auc.reset()
        for keys, labels in ds:
            n = keys.shape[0]
            acts = emb(keys).reshape([n, len(slots) * dim])
            logits = (deep(acts) + wide(acts)).reshape([n])
            loss = nn.functional.binary_cross_entropy_with_logits(
                logits, paddle.to_tensor(labels))
            loss.backward()
            opt.step()
            opt.clear_grad()
            auc.update(1 / (1 + np.exp(-logits.numpy())), labels)
        print(f"epoch {epoch}: loss {float(loss):.4f} "
              f"auc {auc.accumulate():.4f} "
              f"table {len(table)} features")
    rt.save_persistables(os.path.join(tmp, "ps_model"))
    print("saved to", os.path.join(tmp, "ps_model"))


def run_bench(batch_size=512, dim=8, n=20000):
    """bench.py hook: examples/sec through pull -> COMPILED dense step ->
    push after one warmup epoch. The dense model is the framework's own
    nn stack compiled by jit.CompiledTrainStep (donated buffers, fused
    Adam) with input_grads=True, whose extra output — the embedding-
    activation gradient — is pushed back into the C++ tables: the PSGPU
    pull/train/push cycle with the train leg on the accelerator."""
    import time

    import jax
    import jax.numpy as jnp
    from paddle_tpu.jit import CompiledTrainStep

    tmp = tempfile.mkdtemp()
    data = make_slot_files(os.path.join(tmp, "part-0.txt"), n=n)
    slots = [1, 2, 3, 4]
    ds = InMemoryDataset()
    ds.init(batch_size=batch_size, slots=slots, max_per_slot=1)
    ds.set_filelist([data])
    ds.load_into_memory()
    rt = get_ps_runtime()
    table = rt.create_sparse_table(0, dim=dim, sgd_rule="adagrad",
                                   learning_rate=0.1)
    feat = len(slots) * dim

    class WideDeep(nn.Layer):
        def __init__(self):
            super().__init__()
            self.deep = nn.Sequential(
                nn.Linear(feat, 64), nn.ReLU(), nn.Linear(64, 32),
                nn.ReLU(), nn.Linear(32, 1))
            self.wide = nn.Linear(feat, 1)

        def forward(self, acts):
            return (self.deep(acts) + self.wide(acts)).reshape([-1])

    net = WideDeep()
    opt = paddle.optimizer.Adam(1e-3, parameters=net.parameters())
    step = CompiledTrainStep(
        net, nn.functional.binary_cross_entropy_with_logits, opt,
        n_labels=1, input_grads=True)

    from paddle_tpu.ps.pipeline import PullPushPipeline
    pipe = PullPushPipeline(prefetch_depth=8, push_depth=4)
    last = {}
    GROUP = 4   # K pull/train/push cycles per device dispatch: the
    #             relay round trip (8-100 ms) would otherwise floor the
    #             throughput at one batch per RTT

    def pull_fn(batch):
        keys, labels = batch
        bsz = keys.shape[0]
        return (table.pull(keys.astype(np.uint64)).reshape(bsz, feat),
                np.asarray(labels, np.float32))

    group = []

    def _flush_group():
        items = group[:]
        group.clear()
        batches = [(acts, lab) for _, (acts, lab) in items]
        losses, (acts_grads,) = step.run_many(batches,
                                              with_in_grads=True)
        last["loss"] = losses
        return ([k for k, _ in items], acts_grads)

    def step_fn(batch, pulled):
        keys, _ = batch
        push_item = None
        if group and group[0][1][0].shape != pulled[0].shape:
            push_item = _flush_group()   # ragged batch: new group
        group.append((keys, pulled))
        if len(group) >= GROUP:
            assert push_item is None
            push_item = _flush_group()
        return keys.shape[0], push_item

    def push_fn(item):
        keys_list, acts_grads = item
        # the device->host gradient fetch blocks HERE, off the critical
        # path (VERDICT r3 #2: the serial loop paid one sync per batch)
        g = acts_grads.numpy()
        for i, keys in enumerate(keys_list):
            bsz = keys.shape[0]
            table.push(keys.astype(np.uint64),
                       g[i].reshape(bsz, len(slots), 1, dim))

    def epoch():
        group.clear()
        seen = pipe.run(iter(ds), pull_fn, step_fn, push_fn)
        # drain a ragged tail group
        if group:
            push_fn(_flush_group())
        float(jax.device_get(last["loss"]._data[-1]))
        return seen

    epoch()  # warmup/compile
    t0 = time.perf_counter()
    seen = epoch()
    eps = seen / (time.perf_counter() - t0)
    # training AUC on a sample (BASELINE config 5's second metric) via
    # the bucketed metric stack: a real quality signal, not just ex/s
    from paddle_tpu.metric import Auc
    auc = Auc(num_thresholds=2048)
    ds.rewind()
    it = iter(ds)
    for _ in range(8):
        batch = next(it, None)
        if batch is None:
            break
        keys, labels = batch
        acts, lab = pull_fn((keys, labels))
        logits = net(paddle.to_tensor(jnp.asarray(acts)))
        probs = 1.0 / (1.0 + np.exp(-np.asarray(logits.numpy(),
                                                np.float64)))
        preds = np.stack([1.0 - probs, probs], axis=1)
        auc.update(preds, lab.reshape(-1, 1))
    return eps, float(auc.accumulate())


def main_heter(epochs=2, batch_size=512, dim=8, vocab=10000,
               num_shards=4, cache_capacity=4096):
    """Wide&Deep through the HeterPS-style embedding engine
    (`paddle_tpu.ps.heter`): one logical table sharded 4 ways, hot-ID
    cache in front, pulls/pushes dedup-merged — same model code as
    main(), just `SparseEmbedding(engine=...)`."""
    from paddle_tpu.ps import (HeterEmbeddingEngine, LookupService,
                               ShardedSparseTable)
    tmp = tempfile.mkdtemp()
    slots = [1, 2, 3, 4]
    ds = InMemoryDataset()
    ds.init(batch_size=batch_size, slots=slots, max_per_slot=1)
    data = make_slot_files(os.path.join(tmp, "part-0.txt"),
                           vocab=vocab, zipf=1.3)
    ds.set_filelist([data])
    ds.load_into_memory()
    ds.global_shuffle(seed=42)

    table = ShardedSparseTable(num_shards=num_shards, dim=dim,
                               sgd_rule="adagrad", learning_rate=0.1)
    engine = HeterEmbeddingEngine(table, cache_capacity=cache_capacity,
                                  mode="strict")
    emb = SparseEmbedding(dim=dim, engine=engine)
    deep = nn.Sequential(nn.Linear(len(slots) * dim, 64), nn.ReLU(),
                         nn.Linear(64, 32), nn.ReLU(), nn.Linear(32, 1))
    wide = nn.Linear(len(slots) * dim, 1)
    opt = paddle.optimizer.Adam(
        1e-3, parameters=deep.parameters() + wide.parameters())
    auc = paddle.metric.Auc()

    for epoch in range(epochs):
        auc.reset()
        for keys, labels in ds:
            n = keys.shape[0]
            acts = emb(keys).reshape([n, len(slots) * dim])
            logits = (deep(acts) + wide(acts)).reshape([n])
            loss = nn.functional.binary_cross_entropy_with_logits(
                logits, paddle.to_tensor(labels))
            loss.backward()
            opt.step()
            opt.clear_grad()
            auc.update(1 / (1 + np.exp(-logits.numpy())), labels)
        emb.flush()
        print(f"epoch {epoch}: loss {float(loss):.4f} "
              f"auc {auc.accumulate():.4f} "
              f"cache hit ratio {engine.hit_ratio():.3f} "
              f"dedup ratio {engine.dedup_ratio():.3f} "
              f"shards {table.shard_sizes()}")
    # read-only lookup serving over the SAME warm cache
    svc = LookupService(engine)
    probe = np.asarray([100001, 200002, 300003], np.uint64)
    print("lookup service:", svc.lookup(probe).shape,
          "state:", svc.state())
    engine.close()


def run_bench_heter(batch_size=512, dim=8, n_batches=64, vocab=10000,
                    per_slot=4, num_servers=2, cache_capacity=32768):
    """bench.py hook: the engine lane vs the direct-table lane against
    REAL parameter servers (the client/server deployment this example
    documents in its header), on the SAME zipf-skewed key stream —
    recommender traffic is zipfian (the hot head is what the hot-ID
    cache exists for) and slots are multi-valued (user behaviour
    history), so a batch carries heavy intra-batch key duplication.

    direct lane: synchronous RPC pull -> COMPILED step -> grad fetch
    -> RPC push per batch (the plain `SparseEmbedding` order of
    operations over `RemoteSparseTable` — every batch pays two
    full-payload round trips to the servers).
    engine lane: stream-mode `HeterEmbeddingEngine` over the same
    servers — hot ids served from the dense cache, batch N+1's misses
    prefetched over RPC while batch N trains, gradients dedup-merged
    (one wire row per unique key) and drained on a background thread
    up to `staleness_bound` batches late, so both the device->host
    gradient sync AND the push RPC leave the critical path
    (push-as-you-train, the reference AsyncCommunicator window).

    In-process tables are NOT the engine's regime: the native C hash
    table resolves a key in ~100ns, so cache bookkeeping costs more
    than it saves (docs/EMBEDDING.md shows that measurement); the
    engine pays off exactly when pulls cross a process/RPC/disk
    boundary, which is what a real PS deployment does.

    Returns (engine_eps, direct_eps, stats)."""
    import queue
    import threading
    import time

    import jax.numpy as jnp

    from paddle_tpu.jit import CompiledTrainStep
    from paddle_tpu.ps import HeterEmbeddingEngine
    from paddle_tpu.ps.service import (PSClient, PSServer,
                                       RemoteSparseTable)

    slots = [1, 2, 3, 4]
    feat = len(slots) * per_slot * dim
    rng = np.random.RandomState(0)

    def zipf_batch():
        keys = np.empty((batch_size, len(slots), per_slot), np.uint64)
        for j, s in enumerate(slots):
            v = rng.zipf(1.3, (batch_size, per_slot)) % vocab
            keys[:, j, :] = s * 100000 + v
        labels = (rng.rand(batch_size) < 0.5).astype(np.float32)
        return keys, labels

    batches = [zipf_batch() for _ in range(n_batches)]

    class WideDeep(nn.Layer):
        def __init__(self):
            super().__init__()
            self.deep = nn.Sequential(
                nn.Linear(feat, 64), nn.ReLU(), nn.Linear(64, 32),
                nn.ReLU(), nn.Linear(32, 1))
            self.wide = nn.Linear(feat, 1)

        def forward(self, acts):
            return (self.deep(acts) + self.wide(acts)).reshape([-1])

    def build_step():
        paddle.seed(0)
        net = WideDeep()
        opt = paddle.optimizer.Adam(1e-3, parameters=net.parameters())
        return CompiledTrainStep(
            net, nn.functional.binary_cross_entropy_with_logits, opt,
            n_labels=1, input_grads=True)

    def start_servers(table_id):
        servers = [PSServer() for _ in range(num_servers)]
        for s in servers:
            s.register_sparse_table(table_id, dim=dim,
                                    sgd_rule="adagrad",
                                    learning_rate=0.1)
            s.run(background=True)
        client = PSClient([f"127.0.0.1:{s.port}" for s in servers])
        return servers, client

    # K pull/train/push cycles per device dispatch in BOTH lanes (the
    # bench_wide_deep GROUP discipline: per-step dispatch overhead
    # would otherwise dominate this small dense model)
    GROUP = 8
    groups = []
    for g0 in range(0, n_batches, GROUP):
        chunk = batches[g0:g0 + GROUP]
        keys_g = np.concatenate([k for k, _ in chunk])
        groups.append((keys_g, chunk))

    def _run_group(step, acts_flat, chunk):
        """One grouped dispatch -> stacked input grads [K, bsz, feat]."""
        acts = acts_flat.reshape(len(chunk), batch_size, feat)
        stacked = [(jnp.asarray(acts[i]), jnp.asarray(lab))
                   for i, (_, lab) in enumerate(chunk)]
        _, (g,) = step.run_many(stacked, with_in_grads=True)
        return g

    # ---- direct lane: sync RPC pull -> step -> fetch -> RPC push ----
    def run_direct():
        servers, client = start_servers(0)
        table = RemoteSparseTable(client, 0, dim=dim)
        step = build_step()

        def one_pass():
            t0 = time.perf_counter()
            for keys_g, chunk in groups:
                acts_flat = table.pull(keys_g)
                g = _run_group(step, acts_flat, chunk)
                table.push(keys_g, g.numpy().reshape(
                    keys_g.shape[0], len(slots), per_slot, dim))
            return time.perf_counter() - t0
        one_pass()                          # warmup/compile
        # min-of-2 timed passes (BASELINE.md host-variance hardening)
        eps = batch_size * n_batches / min(one_pass(), one_pass())
        client.close()
        for s in servers:
            s.stop()
        return eps

    # ---- engine lane: cached pulls + prefetch + late pushes ----
    def run_engine():
        servers, client = start_servers(0)
        table = RemoteSparseTable(client, 0, dim=dim)
        engine = HeterEmbeddingEngine(table,
                                      cache_capacity=cache_capacity,
                                      mode="stream", staleness_bound=8)
        step = build_step()
        depth = 2                           # device-sync lag (groups)

        def one_pass():
            # stream-mode pushes are thread-safe: a drain thread takes
            # the gradient fetch AND the push RPC off the critical
            # path (bounded queue = the staleness window)
            pq = queue.Queue(maxsize=depth)

            def drain_loop():
                while True:
                    item = pq.get()
                    if item is None:
                        return
                    keys_g, g = item
                    engine.push(keys_g, g.numpy().reshape(
                        keys_g.shape[0], len(slots), per_slot, dim))
            drain = threading.Thread(target=drain_loop, daemon=True)
            drain.start()
            t0 = time.perf_counter()
            for i, (keys_g, chunk) in enumerate(groups):
                acts_flat = engine.pull(keys_g)
                if i + 1 < len(groups):
                    # submit BEFORE the step so the worker's dedup +
                    # miss RPC overlaps the dense compute
                    engine.prefetch(groups[i + 1][0])
                g = _run_group(step, acts_flat, chunk)
                pq.put((keys_g, g))
            pq.put(None)
            drain.join()
            engine.flush()
            return time.perf_counter() - t0
        one_pass()                          # warmup/compile
        # min-of-2 timed passes (BASELINE.md host-variance hardening)
        eps = batch_size * n_batches / min(one_pass(), one_pass())
        stats = {"cache_hit_ratio": round(engine.hit_ratio(), 4),
                 "dedup_ratio": round(engine.dedup_ratio(), 4),
                 "evictions": engine.cache.evictions,
                 "prefetch": {"hits": engine.prefetch_hits,
                              "repairs": engine.prefetch_repairs,
                              "unused": engine.prefetch_unused}}
        engine.close()
        client.close()
        for s in servers:
            s.stop()
        return eps, stats

    direct_eps = run_direct()
    engine_eps, stats = run_engine()
    return engine_eps, direct_eps, stats


if __name__ == "__main__":
    import sys
    if len(sys.argv) > 1 and sys.argv[1] == "heter":
        main_heter()
    else:
        main()
