"""BASELINE config 5: Wide&Deep on Criteo-style slot data with the native
parameter-server engine (C++ tables + DataFeed; AUC metric).

Single-process by default; set the PS env for true client/server mode:
  TRAINING_ROLE=PSERVER PADDLE_PSERVERS_IP_PORT_LIST=... (server)
  TRAINING_ROLE=TRAINER PADDLE_PSERVERS_IP_PORT_LIST=... (trainer)
"""
import os
import tempfile

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.ps import InMemoryDataset, SparseEmbedding
from paddle_tpu.ps.runtime import get_ps_runtime


def make_slot_files(path, n=20000, slots=(1, 2, 3, 4), vocab=10000):
    rng = np.random.RandomState(0)
    with open(path, "w") as f:
        for _ in range(n):
            feats = [rng.randint(0, vocab) for _ in slots]
            label = int((feats[0] % 3 == 0) ^ (feats[1] % 2 == 0))
            f.write(f"{label} " + " ".join(
                f"{s}:{s * 100000 + v}" for s, v in zip(slots, feats))
                + "\n")
    return path


def make_raw_logs(path, n=20000, n_slots=4, vocab=10000):
    """Raw click logs: `<click> <f1> <f2> <f3> <f4>` — NOT the slot
    format; the DataGenerator below parses them (fleet data_generator
    deployment mode)."""
    rng = np.random.RandomState(0)
    with open(path, "w") as f:
        for _ in range(n):
            feats = [rng.randint(0, vocab) for _ in range(n_slots)]
            label = int((feats[0] % 3 == 0) ^ (feats[1] % 2 == 0))
            f.write(f"{label} " + " ".join(map(str, feats)) + "\n")
    return path


class WideDeepGenerator:
    """User parser (fleet data_generator.py parity): raw log line ->
    [(slot_name, [sign...]), ...]."""

    def generate_sample(self, line):
        def local_iter():
            parts = line.split()
            label = int(parts[0])
            yield [("label", [label])] + [
                (f"slot{i+1}", [(i + 1) * 100000 + int(v)])
                for i, v in enumerate(parts[1:])]
        return local_iter


def main(epochs=3, batch_size=512, dim=8, use_data_generator=True):
    from paddle_tpu.ps.data_generator import MultiSlotDataGenerator
    tmp = tempfile.mkdtemp()
    slots = [1, 2, 3, 4]

    ds = InMemoryDataset()
    ds.init(batch_size=batch_size, slots=slots, max_per_slot=1)
    if use_data_generator:
        raw = make_raw_logs(os.path.join(tmp, "raw-0.txt"))

        class Gen(WideDeepGenerator, MultiSlotDataGenerator):
            pass

        gen = Gen()
        gen.set_slots([f"slot{i}" for i in slots])
        ds.load_from_generator(gen, [raw])
    else:
        data = make_slot_files(os.path.join(tmp, "part-0.txt"))
        ds.set_filelist([data])
        ds.load_into_memory()
    ds.global_shuffle(seed=42)
    print("records:", ds.get_memory_data_size())

    rt = get_ps_runtime()
    table = rt.create_sparse_table(0, dim=dim, sgd_rule="adagrad",
                                   learning_rate=0.1)
    emb = SparseEmbedding(dim=dim, table=table)
    deep = nn.Sequential(nn.Linear(len(slots) * dim, 64), nn.ReLU(),
                         nn.Linear(64, 32), nn.ReLU(), nn.Linear(32, 1))
    wide = nn.Linear(len(slots) * dim, 1)
    opt = paddle.optimizer.Adam(
        1e-3, parameters=deep.parameters() + wide.parameters())
    auc = paddle.metric.Auc()

    for epoch in range(epochs):
        auc.reset()
        for keys, labels in ds:
            n = keys.shape[0]
            acts = emb(keys).reshape([n, len(slots) * dim])
            logits = (deep(acts) + wide(acts)).reshape([n])
            loss = nn.functional.binary_cross_entropy_with_logits(
                logits, paddle.to_tensor(labels))
            loss.backward()
            opt.step()
            opt.clear_grad()
            auc.update(1 / (1 + np.exp(-logits.numpy())), labels)
        print(f"epoch {epoch}: loss {float(loss):.4f} "
              f"auc {auc.accumulate():.4f} "
              f"table {len(table)} features")
    rt.save_persistables(os.path.join(tmp, "ps_model"))
    print("saved to", os.path.join(tmp, "ps_model"))


def run_bench(batch_size=512, dim=8, n=20000):
    """bench.py hook: examples/sec through pull -> COMPILED dense step ->
    push after one warmup epoch. The dense model is the framework's own
    nn stack compiled by jit.CompiledTrainStep (donated buffers, fused
    Adam) with input_grads=True, whose extra output — the embedding-
    activation gradient — is pushed back into the C++ tables: the PSGPU
    pull/train/push cycle with the train leg on the accelerator."""
    import time

    import jax
    import jax.numpy as jnp
    from paddle_tpu.jit import CompiledTrainStep

    tmp = tempfile.mkdtemp()
    data = make_slot_files(os.path.join(tmp, "part-0.txt"), n=n)
    slots = [1, 2, 3, 4]
    ds = InMemoryDataset()
    ds.init(batch_size=batch_size, slots=slots, max_per_slot=1)
    ds.set_filelist([data])
    ds.load_into_memory()
    rt = get_ps_runtime()
    table = rt.create_sparse_table(0, dim=dim, sgd_rule="adagrad",
                                   learning_rate=0.1)
    feat = len(slots) * dim

    class WideDeep(nn.Layer):
        def __init__(self):
            super().__init__()
            self.deep = nn.Sequential(
                nn.Linear(feat, 64), nn.ReLU(), nn.Linear(64, 32),
                nn.ReLU(), nn.Linear(32, 1))
            self.wide = nn.Linear(feat, 1)

        def forward(self, acts):
            return (self.deep(acts) + self.wide(acts)).reshape([-1])

    net = WideDeep()
    opt = paddle.optimizer.Adam(1e-3, parameters=net.parameters())
    step = CompiledTrainStep(
        net, nn.functional.binary_cross_entropy_with_logits, opt,
        n_labels=1, input_grads=True)

    from paddle_tpu.ps.pipeline import PullPushPipeline
    pipe = PullPushPipeline(prefetch_depth=8, push_depth=4)
    last = {}
    GROUP = 4   # K pull/train/push cycles per device dispatch: the
    #             relay round trip (8-100 ms) would otherwise floor the
    #             throughput at one batch per RTT

    def pull_fn(batch):
        keys, labels = batch
        bsz = keys.shape[0]
        return (table.pull(keys.astype(np.uint64)).reshape(bsz, feat),
                np.asarray(labels, np.float32))

    group = []

    def _flush_group():
        items = group[:]
        group.clear()
        batches = [(acts, lab) for _, (acts, lab) in items]
        losses, (acts_grads,) = step.run_many(batches,
                                              with_in_grads=True)
        last["loss"] = losses
        return ([k for k, _ in items], acts_grads)

    def step_fn(batch, pulled):
        keys, _ = batch
        push_item = None
        if group and group[0][1][0].shape != pulled[0].shape:
            push_item = _flush_group()   # ragged batch: new group
        group.append((keys, pulled))
        if len(group) >= GROUP:
            assert push_item is None
            push_item = _flush_group()
        return keys.shape[0], push_item

    def push_fn(item):
        keys_list, acts_grads = item
        # the device->host gradient fetch blocks HERE, off the critical
        # path (VERDICT r3 #2: the serial loop paid one sync per batch)
        g = acts_grads.numpy()
        for i, keys in enumerate(keys_list):
            bsz = keys.shape[0]
            table.push(keys.astype(np.uint64),
                       g[i].reshape(bsz, len(slots), 1, dim))

    def epoch():
        group.clear()
        seen = pipe.run(iter(ds), pull_fn, step_fn, push_fn)
        # drain a ragged tail group
        if group:
            push_fn(_flush_group())
        float(jax.device_get(last["loss"]._data[-1]))
        return seen

    epoch()  # warmup/compile
    t0 = time.perf_counter()
    seen = epoch()
    eps = seen / (time.perf_counter() - t0)
    # training AUC on a sample (BASELINE config 5's second metric) via
    # the bucketed metric stack: a real quality signal, not just ex/s
    from paddle_tpu.metric import Auc
    auc = Auc(num_thresholds=2048)
    ds.rewind()
    it = iter(ds)
    for _ in range(8):
        batch = next(it, None)
        if batch is None:
            break
        keys, labels = batch
        acts, lab = pull_fn((keys, labels))
        logits = net(paddle.to_tensor(jnp.asarray(acts)))
        probs = 1.0 / (1.0 + np.exp(-np.asarray(logits.numpy(),
                                                np.float64)))
        preds = np.stack([1.0 - probs, probs], axis=1)
        auc.update(preds, lab.reshape(-1, 1))
    return eps, float(auc.accumulate())


if __name__ == "__main__":
    main()
