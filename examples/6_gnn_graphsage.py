"""GraphSAGE node classification over the native graph engine.

The PGLBox-style loop (`paddle/fluid/framework/fleet/heter_ps/
graph_gpu_ps_table.h` + `graph_sampler_inl.h` reference capability):
the C++ graph store holds adjacency (with edge weights), node features,
and does neighbor sampling on host; the TPU step consumes dense
[batch, k, feat] neighborhood tensors — sampling stays off-device,
compute stays compiled.

Synthetic task: two communities with distinct feature distributions and
mostly intra-community (heavily weighted) edges; a 2-layer mean-aggregate
GraphSAGE should separate them almost perfectly.
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.ps.graph import GraphTable


def build_graph(n_per=200, feat_dim=16, seed=0):
    rng = np.random.RandomState(seed)
    g = GraphTable()
    n = 2 * n_per
    labels = np.array([0] * n_per + [1] * n_per, np.int64)
    feats = rng.randn(n, feat_dim).astype(np.float32) * 0.5
    feats[:n_per, 0] += 1.0
    feats[n_per:, 0] -= 1.0
    nodes = np.arange(1, n + 1, dtype=np.uint64)  # ids are 1-based
    g.set_node_feat(nodes, feats)
    src, dst, w = [], [], []
    for i in range(n):
        for _ in range(6):
            same = rng.rand() < 0.9
            j = rng.randint(0, n_per) + (0 if (i < n_per) == same
                                         else n_per)
            src.append(nodes[i])
            dst.append(nodes[j])
            w.append(5.0 if same else 1.0)  # intra edges sampled 5x more
    g.add_edges_weighted(np.array(src, np.uint64),
                         np.array(dst, np.uint64),
                         np.array(w, np.float32))
    return g, nodes, labels, feat_dim


class GraphSage(nn.Layer):
    def __init__(self, feat_dim, hidden, n_classes=2):
        super().__init__()
        self.l1_self = nn.Linear(feat_dim, hidden)
        self.l1_neigh = nn.Linear(feat_dim, hidden)
        self.l2 = nn.Linear(hidden, n_classes)

    def forward(self, x_self, x_neigh):
        # x_self [B, F]; x_neigh [B, K, F] -> mean aggregate
        h = self.l1_self(x_self) + self.l1_neigh(x_neigh.mean(axis=1))
        return self.l2(nn.functional.relu(h))


def main(epochs=30, batch=128, k=5):
    g, nodes, labels, feat_dim = build_graph()
    net = GraphSage(feat_dim, hidden=32)
    opt = paddle.optimizer.Adam(1e-2, parameters=net.parameters())
    loss_fn = nn.CrossEntropyLoss()
    id2idx = {int(v): i for i, v in enumerate(nodes)}

    rng = np.random.RandomState(1)
    for epoch in range(epochs):
        perm = rng.permutation(nodes.size)
        losses = []
        for lo in range(0, nodes.size, batch):
            bidx = perm[lo:lo + batch]
            bn = nodes[bidx]
            neigh, _deg = g.sample_neighbors(bn, k)  # host C++ sampling
            x_self = g.get_node_feat(bn, feat_dim)
            x_neigh = g.get_node_feat(neigh.reshape(-1), feat_dim) \
                .reshape(bn.size, k, feat_dim)
            y = labels[bidx].reshape(-1, 1)
            logits = net(paddle.to_tensor(x_self),
                         paddle.to_tensor(x_neigh))
            loss = loss_fn(logits, paddle.to_tensor(y))
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        if epoch % 10 == 0 or epoch == epochs - 1:
            neigh, _ = g.sample_neighbors(nodes, k)
            pred = net(paddle.to_tensor(g.get_node_feat(nodes, feat_dim)),
                       paddle.to_tensor(g.get_node_feat(
                           neigh.reshape(-1), feat_dim).reshape(
                           nodes.size, k, feat_dim)))
            acc = (pred.numpy().argmax(-1) == labels).mean()
            print(f"epoch {epoch}: loss {np.mean(losses):.4f} "
                  f"acc {acc:.3f}")
    return acc


if __name__ == "__main__":
    final = main()
    assert final > 0.9, f"GraphSAGE failed to separate communities: {final}"
