"""Expert-parallel MoE GPT (ISSUE 10, docs/MOE.md): train the hybrid
MoE trainer over the ("dp","pp","mp","ep") mesh, then serve the same
model class through the one-compile mixed step with TP x EP sharding.

Runs on the CPU virtual mesh:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      JAX_PLATFORMS=cpu python examples/8_gpt_moe.py
"""
import argparse

import numpy as np
import jax

from paddle_tpu.parallel.hybrid_gpt import GPTConfig, HybridGPT
from paddle_tpu.profiler import metrics as pm


def main_train(ep=2, dp=1, steps=6, experts=4, top_k=2):
    """MoE pretraining: experts sharded over the ep axis, fixed
    [E, C, d] dispatch tensors riding all_to_all inside the ONE
    compiled step; per-step routing stats printed."""
    cfg = GPTConfig(vocab_size=512, seq_len=64, d_model=64, n_heads=4,
                    n_layers=4, dp=dp, ep=ep, moe_num_experts=experts,
                    moe_top_k=top_k, moe_capacity_factor=2.0,
                    remat=False, compute_dtype=jax.numpy.float32)
    n = cfg.dp * cfg.pp * cfg.mp * cfg.ep
    if jax.device_count() < n:
        raise SystemExit(f"need {n} devices; jax sees "
                         f"{jax.device_count()}")
    trainer = HybridGPT(cfg)
    params, opt = trainer.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    batch = 4 * cfg.dp * cfg.ep
    for step in range(steps):
        tok = rng.randint(0, cfg.vocab_size,
                          (batch, cfg.seq_len)).astype(np.int32)
        tok_d, lab_d = trainer.shard_data(tok, tok)
        params, opt, loss = trainer.train_step(params, opt, tok_d,
                                               lab_d, step_num=step + 1)
        st = jax.device_get(trainer.last_moe_stats)
        counts = np.asarray(st["counts"], np.int64)
        print(f"step {step}: loss {float(jax.device_get(loss)):.4f} "
              f"balance {float(st['balance']):.3f} "
              f"z {float(st['z']):.3f} dropped {int(st['dropped'])} "
              f"expert_tokens {counts.tolist()} "
              f"entropy {pm.moe_utilization_entropy(counts):.3f} "
              f"(E={experts} k={top_k} ep={cfg.ep} dp={cfg.dp})")
    return params


def main_serve(tensor_parallel=2, expert_parallel=2, n_req=6,
               max_new=16):
    """MoE serving: per-token routing inside the ONE jitted mixed step
    (fixed expert-capacity slots), experts sharded over ep and heads
    over mp on a 2-D (ep, mp) mesh — token-identical to the EP=1
    single-chip engine."""
    import paddle_tpu as paddle
    from paddle_tpu import inference
    from paddle_tpu.models.gpt import GPTForGeneration

    paddle.seed(0)
    model = GPTForGeneration(vocab_size=512, hidden_size=64,
                             num_layers=2, num_attention_heads=4,
                             max_position_embeddings=256,
                             compute_dtype="float32",
                             moe=dict(num_expert=4, top_k=2,
                                      capacity_factor=2.0))
    model.eval()
    cfg = inference.Config()
    cfg.enable_continuous_batching(
        max_slots=4, block_size=8, max_seq_len=128,
        tensor_parallel=tensor_parallel,
        expert_parallel=expert_parallel)
    engine = inference.create_serving_engine(cfg, model)
    rng = np.random.RandomState(7)
    prompts = [rng.randint(1, 512, int(n)).tolist()
               for n in rng.randint(4, 24, n_req)]
    outs = engine.generate_batch(prompts, max_new_tokens=max_new)
    for i, o in enumerate(outs):
        print(f"req {i}: {len(o)} tokens -> {o[:8]}...")
    print(f"expert tokens {engine.moe_expert_counts.astype(int).tolist()} "
          f"utilization entropy {engine.moe_utilization_entropy():.3f} "
          f"dropped {int(engine.moe_dropped_total)} "
          f"(tp={tensor_parallel} ep={expert_parallel})")
    return outs


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=("train", "serve", "both"),
                    default="both")
    ap.add_argument("--ep", type=int, default=2)
    ap.add_argument("--tp", type=int, default=2)
    args = ap.parse_args()
    if args.mode in ("train", "both"):
        main_train(ep=args.ep)
    if args.mode in ("serve", "both"):
        main_serve(tensor_parallel=args.tp, expert_parallel=args.ep)
