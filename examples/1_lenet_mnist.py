"""BASELINE config 1: LeNet MNIST via Model.fit (hapi + compiled step)."""
import paddle_tpu as paddle
from paddle_tpu.vision.models import LeNet
from paddle_tpu.vision.datasets import MNIST


def main():
    model = paddle.Model(LeNet())
    opt = paddle.optimizer.Adam(1e-3, parameters=model.parameters())
    model.prepare(opt, paddle.nn.CrossEntropyLoss(),
                  paddle.metric.Accuracy())
    model.fit(MNIST(mode="train"), epochs=2, batch_size=64,
              verbose=2, drop_last=True)
    print(model.evaluate(MNIST(mode="test"), batch_size=64, verbose=0))


if __name__ == "__main__":
    main()
