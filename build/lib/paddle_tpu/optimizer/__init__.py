"""paddle_tpu.optimizer — `python/paddle/optimizer/` parity."""
from .optimizer import Optimizer  # noqa: F401
from .optimizers import (  # noqa: F401
    SGD, Momentum, Adam, AdamW, Adagrad, RMSProp, Adadelta, Adamax, Lamb,
)
from . import lr  # noqa: F401
