"""Optimizer base.

Parity: `python/paddle/optimizer/optimizer.py:120` (`Optimizer`:
`_create_accumulators`, `_append_optimize_op`, `step`, `minimize`,
`clear_grad`, state_dict) — with the TPU-native twist that `step()` runs ONE
fused, jit-compiled update over the whole parameter set (the capability of
the reference's `merged_adam` / `multi_tensor_adam`
`paddle/phi/kernels/gpu/adam_kernel.cu` + `fused_adam`), instead of one
kernel launch per parameter. Grad clipping (global norm) and weight decay
fold into the same compiled step.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..core import autograd
from .lr import LRScheduler


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 multi_precision=False):
        if parameters is not None:
            parameters = list(parameters)
            if parameters and isinstance(parameters[0], dict):
                # param groups: flatten (group-specific lr multipliers kept
                # via optimize_attr)
                flat = []
                for group in parameters:
                    for p in group["params"]:
                        if "learning_rate" in group:
                            p.optimize_attr["learning_rate"] = \
                                group["learning_rate"]
                        if "weight_decay" in group:
                            p.optimize_attr["weight_decay"] = \
                                _wd_coeff(group["weight_decay"])
                        flat.append(p)
                parameters = flat
        self._parameter_list = parameters
        self._learning_rate = learning_rate
        self._weight_decay = _wd_coeff(weight_decay)
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._accumulators = {}  # id(param) -> dict name->jnp array
        self._step_count = 0
        self._jit_cache = {}
        self._name = name or type(self).__name__

    # ------------------------------------------------------------- lr
    def get_lr(self):
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate.get_lr())
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._learning_rate = float(value)

    def _lr_scheduler_step(self):
        # paddle semantics: scheduler.step() is user-driven; nothing here.
        pass

    # ---------------------------------------------------- per-opt hooks
    def _accumulator_specs(self, param):
        """Return dict name -> init array for a parameter."""
        return {}

    def _single_update(self, p, g, accums, lr, t, wd):
        """Pure function: returns (new_p, new_accums_dict)."""
        raise NotImplementedError

    # ------------------------------------------------------------ step
    def _params_with_grad(self):
        if self._parameter_list is None:
            raise ValueError(
                "optimizer built without a parameter list; in dygraph mode "
                "pass parameters=model.parameters()")
        return [p for p in self._parameter_list
                if (not p.stop_gradient) and p.grad is not None]

    def _get_accums(self, p):
        key = id(p)
        if key not in self._accumulators:
            self._accumulators[key] = {
                name: init for name, init in
                self._accumulator_specs(p).items()}
        return self._accumulators[key]

    def _build_fused(self, n, clip_kind, clip_value, wds, lr_mults):
        """Compile one whole-parameter-set update. Keyed by list structure."""
        single = self._single_update

        def fused(params, grads, accums, lr, t):
            # global-norm clip over the full grad set, inside the jit
            if clip_kind == "global_norm":
                gnorm = jnp.sqrt(sum(
                    jnp.sum(jnp.square(g.astype(jnp.float32)))
                    for g in grads))
                scale = jnp.minimum(1.0, clip_value / (gnorm + 1e-6))
                grads = [g * scale.astype(g.dtype) for g in grads]
            elif clip_kind == "norm":
                new_grads = []
                for g in grads:
                    n_ = jnp.sqrt(jnp.sum(jnp.square(
                        g.astype(jnp.float32))))
                    s = jnp.minimum(1.0, clip_value / (n_ + 1e-6))
                    new_grads.append(g * s.astype(g.dtype))
                grads = new_grads
            elif clip_kind == "value":
                grads = [jnp.clip(g, -clip_value, clip_value) for g in grads]
            new_ps, new_accs = [], []
            for p, g, acc, wd, lm in zip(params, grads, accums, wds,
                                         lr_mults):
                np_, nacc = single(p, g, acc, lr * lm, t, wd)
                new_ps.append(np_)
                new_accs.append(nacc)
            return new_ps, new_accs
        return jax.jit(fused, donate_argnums=(0, 2))

    def step(self):
        params = self._params_with_grad()
        if not params:
            return
        grads = [p.grad._data for p in params]
        accums = [self._get_accums(p) for p in params]
        param_arrays = [p._data for p in params]

        clip_kind, clip_value = _clip_spec(self._grad_clip)
        # paddle: parameters with their own regularizer override the global
        wds = tuple(
            p.optimize_attr.get("weight_decay", self._weight_decay)
            if p.regularizer is None else _wd_coeff(p.regularizer)
            for p in params)
        lr_mults = tuple(p.optimize_attr.get("learning_rate", 1.0)
                         for p in params)

        key = (len(params), clip_kind, clip_value, wds, lr_mults,
               tuple(tuple(sorted(a.keys())) for a in accums))
        if key not in self._jit_cache:
            self._jit_cache[key] = self._build_fused(
                len(params), clip_kind, clip_value, wds, lr_mults)
        fused = self._jit_cache[key]

        lr = jnp.asarray(self.get_lr(), jnp.float32)
        t = jnp.asarray(self._step_count + 1, jnp.float32)
        new_params, new_accums = fused(param_arrays, grads, accums, lr, t)
        for p, np_, nacc in zip(params, new_params, new_accums):
            p._data = np_
            self._accumulators[id(p)] = nacc
        self._step_count += 1

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        if loss._grad_node is not None or not loss.stop_gradient:
            loss.backward()
        self.step()
        return None, None

    def clear_grad(self, set_to_zero=False):
        if self._parameter_list is None:
            return
        for p in self._parameter_list:
            p.clear_grad()

    clear_gradients = clear_grad

    # ----------------------------------------------------------- state
    def state_dict(self):
        state = {"step_count": self._step_count}
        zero_shapes = getattr(self, "_zero_accum_shapes", {})
        if self._parameter_list is not None:
            for i, p in enumerate(self._parameter_list):
                acc = self._accumulators.get(id(p))
                if acc:
                    shapes = zero_shapes.get(id(p), {})
                    for name, arr in acc.items():
                        a = np.asarray(arr)
                        if name in shapes and a.ndim == 1 and \
                                tuple(a.shape) != tuple(shapes[name][0]):
                            # ZeRO flat layout -> logical shape for the
                            # checkpoint (portable across shardings)
                            shape, dtype = shapes[name]
                            n = int(np.prod(shape)) if shape else 1
                            a = a[:n].reshape(shape).astype(dtype)
                        state[f"{p.name or i}_{name}"] = a
        if isinstance(self._learning_rate, LRScheduler):
            state["LR_Scheduler"] = self._learning_rate.state_dict()
        return state

    def set_state_dict(self, state):
        self._step_count = int(state.get("step_count", 0))
        if isinstance(self._learning_rate, LRScheduler) and \
                "LR_Scheduler" in state:
            self._learning_rate.set_state_dict(state["LR_Scheduler"])
        if self._parameter_list is not None:
            for i, p in enumerate(self._parameter_list):
                specs = self._accumulator_specs(p)
                loaded = {}
                for name in specs:
                    k = f"{p.name or i}_{name}"
                    if k in state:
                        loaded[name] = jnp.asarray(state[k])
                if loaded:
                    acc = self._get_accums(p)
                    for name, arr in loaded.items():
                        cur = acc.get(name)
                        if cur is not None and cur.ndim == 1 and \
                                arr.shape != cur.shape:
                            # live accums are in the ZeRO flat layout
                            # (CompiledTrainStep); re-flatten the logical
                            # checkpoint array to match
                            flat = jnp.pad(
                                arr.reshape(-1).astype(cur.dtype),
                                (0, cur.shape[0] - arr.size))
                            arr = jax.device_put(flat, cur.sharding)
                        acc[name] = arr

    @property
    def _param_groups(self):
        return self._parameter_list


def _wd_coeff(weight_decay):
    if weight_decay is None:
        return 0.0
    if isinstance(weight_decay, (int, float)):
        return float(weight_decay)
    # regularizer.L2Decay
    return float(getattr(weight_decay, "_coeff",
                         getattr(weight_decay, "coeff", 0.0)))


def _clip_spec(grad_clip):
    if grad_clip is None:
        return None, 0.0
    name = type(grad_clip).__name__
    if name == "ClipGradByGlobalNorm":
        return "global_norm", float(grad_clip.clip_norm)
    if name == "ClipGradByNorm":
        return "norm", float(grad_clip.clip_norm)
    if name == "ClipGradByValue":
        return "value", float(grad_clip.max)
    return None, 0.0
