"""Concrete optimizers.

Parity: `python/paddle/optimizer/{sgd,momentum,adam,adamw,adagrad,rmsprop,
adadelta,lamb,adamax}.py` over PHI optimizer kernels
(`paddle/phi/kernels/gpu/adam_kernel.cu`, `momentum_kernel.h`,
`lamb_kernel.h`, …). Each `_single_update` is the pure-functional form the
fused jitted step maps over all parameters.

Convention: non-AdamW optimizers apply weight decay as L2 regularisation
added to the gradient (reference `paddle/fluid/regularizer.py` appended to
grad); AdamW applies decoupled decay.
"""
from __future__ import annotations

import jax.numpy as jnp

from .optimizer import Optimizer


def _l2(g, p, wd):
    if wd:
        return g + wd * p.astype(g.dtype)
    return g


class SGD(Optimizer):
    def _single_update(self, p, g, accums, lr, t, wd):
        g = _l2(g.astype(jnp.float32), p, wd)
        return (p - lr * g).astype(p.dtype), accums


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, rescale_grad=1.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _accumulator_specs(self, param):
        return {"velocity": jnp.zeros(param._data.shape, jnp.float32)}

    def _single_update(self, p, g, accums, lr, t, wd):
        g = _l2(g.astype(jnp.float32), p, wd)
        v = self._momentum * accums["velocity"] + g
        if self._use_nesterov:
            update = g + self._momentum * v
        else:
            update = v
        return (p - lr * update).astype(p.dtype), {"velocity": v}


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1 = float(beta1) if not hasattr(beta1, "item") else \
            float(beta1.item())
        self._beta2 = float(beta2) if not hasattr(beta2, "item") else \
            float(beta2.item())
        self._epsilon = epsilon

    def _accumulator_specs(self, param):
        return {"moment1": jnp.zeros(param._data.shape, jnp.float32),
                "moment2": jnp.zeros(param._data.shape, jnp.float32)}

    def _decoupled_wd(self):
        return 0.0

    def _single_update(self, p, g, accums, lr, t, wd):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        gf = g.astype(jnp.float32)
        dwd = self._decoupled_wd()
        if not dwd:
            gf = _l2(gf, p, wd)
        m = b1 * accums["moment1"] + (1 - b1) * gf
        v = b2 * accums["moment2"] + (1 - b2) * gf * gf
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        pf = p.astype(jnp.float32)
        if dwd and wd:
            pf = pf * (1.0 - lr * wd)
        new_p = pf - lr * mhat / (jnp.sqrt(vhat) + eps)
        return new_p.astype(p.dtype), {"moment1": m, "moment2": v}


class AdamW(Adam):
    """Decoupled weight decay (`python/paddle/optimizer/adamw.py`)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, multi_precision,
                         name)
        self._apply_decay_param_fun = apply_decay_param_fun

    def _decoupled_wd(self):
        return 1.0

    def step(self):
        if self._apply_decay_param_fun is not None and \
                self._parameter_list is not None:
            for p in self._parameter_list:
                if not self._apply_decay_param_fun(p.name or ""):
                    p.optimize_attr["weight_decay"] = 0.0
        super().step()


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None,
                 initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _accumulator_specs(self, param):
        return {"moment": jnp.full(param._data.shape, self._init_acc,
                                   jnp.float32)}

    def _single_update(self, p, g, accums, lr, t, wd):
        gf = _l2(g.astype(jnp.float32), p, wd)
        moment = accums["moment"] + gf * gf
        new_p = p.astype(jnp.float32) - lr * gf / (
            jnp.sqrt(moment) + self._epsilon)
        return new_p.astype(p.dtype), {"moment": moment}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _accumulator_specs(self, param):
        shape = param._data.shape
        specs = {"mean_square": jnp.zeros(shape, jnp.float32),
                 "momentum_acc": jnp.zeros(shape, jnp.float32)}
        if self._centered:
            specs["mean_grad"] = jnp.zeros(shape, jnp.float32)
        return specs

    def _single_update(self, p, g, accums, lr, t, wd):
        gf = _l2(g.astype(jnp.float32), p, wd)
        ms = self._rho * accums["mean_square"] + (1 - self._rho) * gf * gf
        out = {"mean_square": ms}
        if self._centered:
            mg = self._rho * accums["mean_grad"] + (1 - self._rho) * gf
            denom = jnp.sqrt(ms - mg * mg + self._epsilon)
            out["mean_grad"] = mg
        else:
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._momentum * accums["momentum_acc"] + lr * gf / denom
        out["momentum_acc"] = mom
        return (p.astype(jnp.float32) - mom).astype(p.dtype), out


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._epsilon, self._rho = epsilon, rho

    def _accumulator_specs(self, param):
        shape = param._data.shape
        return {"avg_squared_grad": jnp.zeros(shape, jnp.float32),
                "avg_squared_update": jnp.zeros(shape, jnp.float32)}

    def _single_update(self, p, g, accums, lr, t, wd):
        gf = _l2(g.astype(jnp.float32), p, wd)
        rho, eps = self._rho, self._epsilon
        asg = rho * accums["avg_squared_grad"] + (1 - rho) * gf * gf
        update = gf * jnp.sqrt(accums["avg_squared_update"] + eps) / \
            jnp.sqrt(asg + eps)
        asu = rho * accums["avg_squared_update"] + (1 - rho) * update ** 2
        new_p = p.astype(jnp.float32) - lr * update
        return new_p.astype(p.dtype), {"avg_squared_grad": asg,
                                       "avg_squared_update": asu}


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _accumulator_specs(self, param):
        shape = param._data.shape
        return {"moment": jnp.zeros(shape, jnp.float32),
                "inf_norm": jnp.zeros(shape, jnp.float32)}

    def _single_update(self, p, g, accums, lr, t, wd):
        gf = _l2(g.astype(jnp.float32), p, wd)
        b1, b2 = self._beta1, self._beta2
        m = b1 * accums["moment"] + (1 - b1) * gf
        u = jnp.maximum(b2 * accums["inf_norm"], jnp.abs(gf))
        new_p = p.astype(jnp.float32) - (lr / (1 - b1 ** t)) * m / \
            (u + self._epsilon)
        return new_p.astype(p.dtype), {"moment": m, "inf_norm": u}


class Lamb(Optimizer):
    """LAMB (`python/paddle/optimizer/lamb.py`,
    `paddle/phi/kernels/lamb_kernel.h`) — BERT-large batch scaling."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 name=None):
        super().__init__(learning_rate, parameters, lamb_weight_decay,
                         grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _accumulator_specs(self, param):
        shape = param._data.shape
        return {"moment1": jnp.zeros(shape, jnp.float32),
                "moment2": jnp.zeros(shape, jnp.float32)}

    def _single_update(self, p, g, accums, lr, t, wd):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        gf = g.astype(jnp.float32)
        pf = p.astype(jnp.float32)
        m = b1 * accums["moment1"] + (1 - b1) * gf
        v = b2 * accums["moment2"] + (1 - b2) * gf * gf
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        r = mhat / (jnp.sqrt(vhat) + eps) + wd * pf
        w_norm = jnp.linalg.norm(pf)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        new_p = pf - lr * trust * r
        return new_p.astype(p.dtype), {"moment1": m, "moment2": v}

    def step(self):
        if self._exclude_fn is not None and self._parameter_list is not None:
            for p in self._parameter_list:
                if self._exclude_fn(p):
                    p.optimize_attr["weight_decay"] = 0.0
        super().step()
