"""paddle_tpu.audio — `python/paddle/audio/` parity essentials.

Feature extractors (spectrogram / mel / MFCC) over jnp FFT (XLA),
matching paddle.audio.features layer APIs.
"""
from . import functional  # noqa: F401
from . import features  # noqa: F401
