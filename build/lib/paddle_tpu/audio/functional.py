"""Audio DSP functionals (paddle.audio.functional parity)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..ops._helpers import as_tensor, unary


def hz_to_mel(freq, htk=False):
    if htk:
        return 2595.0 * np.log10(1.0 + np.asarray(freq) / 700.0)
    f = np.asarray(freq, dtype=np.float64)
    f_min, f_sp = 0.0, 200.0 / 3
    mels = (f - f_min) / f_sp
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return np.where(f >= min_log_hz,
                    min_log_mel + np.log(f / min_log_hz) / logstep, mels)


def mel_to_hz(mel, htk=False):
    if htk:
        return 700.0 * (10.0 ** (np.asarray(mel) / 2595.0) - 1.0)
    m = np.asarray(mel, dtype=np.float64)
    f_min, f_sp = 0.0, 200.0 / 3
    freqs = f_min + f_sp * m
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return np.where(m >= min_log_mel,
                    min_log_hz * np.exp(logstep * (m - min_log_mel)),
                    freqs)


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney"):
    f_max = f_max or sr / 2
    n_freqs = n_fft // 2 + 1
    fft_freqs = np.linspace(0, sr / 2, n_freqs)
    mel_pts = np.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk),
                          n_mels + 2)
    hz_pts = mel_to_hz(mel_pts, htk)
    fb = np.zeros((n_mels, n_freqs))
    for m in range(n_mels):
        lo, c, hi = hz_pts[m], hz_pts[m + 1], hz_pts[m + 2]
        up = (fft_freqs - lo) / max(c - lo, 1e-9)
        down = (hi - fft_freqs) / max(hi - c, 1e-9)
        fb[m] = np.maximum(0, np.minimum(up, down))
    if norm == "slaney":
        enorm = 2.0 / (hz_pts[2:] - hz_pts[:-2])
        fb *= enorm[:, None]
    return Tensor(fb.astype(np.float32))


def get_window(window, win_length, fftbins=True):
    n = win_length
    # fftbins=True -> periodic window (denominator n); False -> symmetric
    denom = n if fftbins else max(n - 1, 1)
    if window in ("hann", "hanning"):
        w = 0.5 - 0.5 * np.cos(2 * np.pi * np.arange(n) / denom)
    elif window == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * np.pi * np.arange(n) / denom)
    elif window in ("rect", "boxcar", "ones"):
        w = np.ones(n)
    else:
        raise ValueError(f"unknown window {window}")
    return Tensor(w.astype(np.float32))


def power_to_db(x, ref_value=1.0, amin=1e-10, top_db=80.0):
    x = as_tensor(x)

    def _fn(a):
        db = 10.0 * jnp.log10(jnp.maximum(a, amin) / ref_value)
        if top_db is not None:
            db = jnp.maximum(db, jnp.max(db) - top_db)
        return db
    return unary("power_to_db", _fn, x)


def create_dct(n_mfcc, n_mels, norm="ortho"):
    k = np.arange(n_mfcc)[:, None]
    n = np.arange(n_mels)[None, :]
    dct = np.cos(np.pi / n_mels * (n + 0.5) * k)
    if norm == "ortho":
        dct[0] *= 1.0 / math.sqrt(2)
        dct *= math.sqrt(2.0 / n_mels)
    return Tensor(dct.astype(np.float32))
