"""Audio feature layers (paddle.audio.features parity): Spectrogram,
MelSpectrogram, LogMelSpectrogram, MFCC."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dispatch
from ..nn.layer_base import Layer
from ..ops._helpers import as_tensor
from . import functional as AF


def _stft_mag(a, n_fft, hop, win, center, pad_mode):
    # a: [B, T] -> power spectrogram [B, n_fft//2+1, frames]
    if center:
        pad = n_fft // 2
        jmode = {"reflect": "reflect", "constant": "constant",
                 "replicate": "edge"}.get(pad_mode, "reflect")
        a = jnp.pad(a, ((0, 0), (pad, pad)), mode=jmode)
    T = a.shape[1]
    n_frames = 1 + (T - n_fft) // hop
    idx = (jnp.arange(n_frames)[:, None] * hop
           + jnp.arange(n_fft)[None, :])
    frames = a[:, idx] * win[None, None, :]        # [B, F, n_fft]
    spec = jnp.fft.rfft(frames, axis=-1)           # [B, F, n_bins]
    power = jnp.abs(spec) ** 2
    return jnp.swapaxes(power, 1, 2)               # [B, n_bins, F]


class Spectrogram(Layer):
    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.center = center
        self.pad_mode = pad_mode
        self.power = power
        self.register_buffer("window",
                             AF.get_window(window, self.win_length))

    def forward(self, x):
        x = as_tensor(x)
        win = self.window
        n_fft, hop = self.n_fft, self.hop
        p = self.power
        center, pad_mode = self.center, self.pad_mode

        def _fn(a, w):
            if w.shape[0] < n_fft:
                # center the window inside the FFT frame (librosa/paddle)
                lo = (n_fft - w.shape[0]) // 2
                w = jnp.pad(w, (lo, n_fft - w.shape[0] - lo))
            out = _stft_mag(a, n_fft, hop, w, center, pad_mode)
            if p != 2.0:
                out = out ** (p / 2.0)
            return out
        return dispatch.apply("spectrogram", _fn, (x, win))


class MelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, n_mels=64,
                 f_min=50.0, f_max=None, htk=False, norm="slaney",
                 dtype="float32"):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                       window, power)
        self.register_buffer(
            "fbank", AF.compute_fbank_matrix(sr, n_fft, n_mels, f_min,
                                             f_max, htk, norm))

    def forward(self, x):
        spec = self.spectrogram(x)
        fb = self.fbank

        def _fn(s, f):
            return jnp.einsum("mf,bft->bmt", f, s)
        return dispatch.apply("mel", _fn, (spec, fb))


class LogMelSpectrogram(MelSpectrogram):
    def __init__(self, *a, ref_value=1.0, amin=1e-10, top_db=None, **k):
        super().__init__(*a, **k)
        self.ref_value, self.amin, self.top_db = ref_value, amin, top_db

    def forward(self, x):
        mel = super().forward(x)
        return AF.power_to_db(mel, self.ref_value, self.amin, self.top_db)


class MFCC(Layer):
    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, n_mels=64, **k):
        super().__init__()
        self.logmel = LogMelSpectrogram(sr=sr, n_fft=n_fft,
                                        n_mels=n_mels, **k)
        self.register_buffer("dct", AF.create_dct(n_mfcc, n_mels))

    def forward(self, x):
        lm = self.logmel(x)
        d = self.dct

        def _fn(m, dct):
            return jnp.einsum("km,bmt->bkt", dct, m)
        return dispatch.apply("mfcc", _fn, (lm, d))
