"""Global flags registry.

Parity: the reference's gflags tier (`paddle/fluid/platform/flags.cc` — 74
`PADDLE_DEFINE_EXPORTED_*` runtime knobs, exported to python via
`global_value_getter_setter.cc` and settable by `FLAGS_*` env or
`paddle.set_flags`).
"""
from __future__ import annotations

import os

_FLAGS = {
    # numerics / debugging (SURVEY §5.2)
    "FLAGS_check_nan_inf": False,
    "FLAGS_benchmark": False,
    "FLAGS_cudnn_deterministic": True,   # TPU is deterministic by default
    # memory
    "FLAGS_fraction_of_gpu_memory_to_use": 0.92,
    "FLAGS_allocator_strategy": "auto_growth",
    # eager/debug
    "FLAGS_enable_unused_var_check": False,
    "FLAGS_call_stack_level": 1,
    # TPU-native knobs. Pallas (splash) flash attention is the default
    # on TPU: trace-measured 2.1x faster fwd+bwd than XLA's fused
    # attention (docs/gpt_perf_analysis.md); off-TPU the XLA path runs
    # regardless of this flag.
    "FLAGS_use_pallas_flash_attention": True,
    "FLAGS_jit_compile_train_step": True,
}


def _load_env():
    for k in list(_FLAGS):
        if k in os.environ:
            v = os.environ[k]
            cur = _FLAGS[k]
            if isinstance(cur, bool):
                _FLAGS[k] = v.lower() in ("1", "true", "yes")
            elif isinstance(cur, float):
                _FLAGS[k] = float(v)
            elif isinstance(cur, int):
                _FLAGS[k] = int(v)
            else:
                _FLAGS[k] = v


_load_env()


def set_flags(flags: dict):
    """paddle.set_flags parity."""
    for k, v in flags.items():
        _FLAGS[k] = v
    if flags.get("FLAGS_use_pallas_flash_attention"):
        os.environ["PADDLE_TPU_PALLAS_FLASH"] = "1"
    elif "FLAGS_use_pallas_flash_attention" in flags:
        os.environ["PADDLE_TPU_PALLAS_FLASH"] = "0"


def get_flags(keys):
    """paddle.get_flags parity."""
    if isinstance(keys, str):
        keys = [keys]
    return {k: _FLAGS.get(k) for k in keys}


def check_nan_inf_enabled() -> bool:
    return bool(_FLAGS.get("FLAGS_check_nan_inf"))
