"""Recompute (activation checkpointing) user API.

Parity: `python/paddle/distributed/fleet/recompute/recompute.py:229`
(`recompute(function, *args)`) + `recompute_hybrid.py`. TPU-native: the
eager tape records ONE GradNode whose vjp re-runs the function under
`jax.vjp` of a `jax.checkpoint`-wrapped pure function — forward saves
only the inputs; backward recomputes activations (XLA remat).
"""
from __future__ import annotations

import jax

from ..core import autograd
from ..core import dispatch
from ..core import random as rng_mod
from ..core.tensor import Tensor


def recompute(function, *args, **kwargs):
    """All positional Tensor args participate in autograd; the function
    runs under no-tape with traced values, wrapped in jax.checkpoint."""
    preserve = kwargs.pop("preserve_rng_state", True)
    tensor_args = [a for a in args if isinstance(a, Tensor)]
    key = rng_mod.next_key() if preserve else rng_mod.get_rng_state()

    def pure(*arrays):
        it = iter(arrays)
        wrapped = [Tensor(next(it)) if isinstance(a, Tensor) else a
                   for a in args]
        with rng_mod.functional_rng(key), autograd.no_grad():
            out = function(*wrapped, **kwargs)
        if isinstance(out, (list, tuple)):
            return tuple(o._data if isinstance(o, Tensor) else o
                         for o in out)
        return out._data if isinstance(out, Tensor) else out

    ckpt = jax.checkpoint(pure)
    return dispatch.apply("recompute", ckpt, tuple(tensor_args))
