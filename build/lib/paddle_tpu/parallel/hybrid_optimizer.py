"""HybridParallelOptimizer.

Parity: `python/paddle/distributed/fleet/meta_optimizers/dygraph_optimizer/
hybrid_parallel_optimizer.py:172` — wraps the user optimizer; in the
reference it fuses DP grad allreduce, sharding and a cross-axis global-norm
clip. TPU-native: grad reduction happens inside the compiled step (GSPMD /
shard_map transpose), so this wrapper mostly delegates; it keeps the fleet
API and carries the sharding (ZeRO) configuration into the compiled
trainers.
"""
from __future__ import annotations


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg=None, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy
        if strategy is not None and getattr(strategy, "sharding", False):
            optimizer._zero_stage = strategy.sharding_configs.get("stage", 1)

    def __getattr__(self, name):
        return getattr(self._inner_opt, name)

    def step(self):
        self._inner_opt.step()

    def minimize(self, loss, *a, **k):
        return self._inner_opt.minimize(loss, *a, **k)

    def clear_grad(self, *a, **k):
        self._inner_opt.clear_grad(*a, **k)

    clear_gradients = clear_grad

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)
