"""Distributed environment + device mesh management.

Parity: `python/paddle/distributed/parallel.py:104 init_parallel_env` (+
TCPStore rendezvous `distributed/store/tcp_store.h:120`, NCCL comm-id
bootstrap `platform/gen_comm_id_helper.cc`).

TPU-native (SURVEY.md §5.8): `jax.distributed.initialize` is the
coordination service (subsumes TCPStore / gen_nccl_id / gloo barriers); the
"world" is jax's global device set. Within one host, the N local TPU chips
are N "ranks" under SPMD — collectives compile onto ICI. `global_mesh()`
builds the `jax.sharding.Mesh` every parallel layer shards over.
"""
from __future__ import annotations

import os

import numpy as np
import jax

_initialized = False
_mesh_cache = {}


class ParallelEnv:
    """paddle.distributed.ParallelEnv parity."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def local_rank(self):
        return get_rank() % max(jax.local_device_count(), 1)

    @property
    def nranks(self):
        return get_world_size()

    @property
    def dev_id(self):
        return self.local_rank


def init_parallel_env():
    """Initialise multi-host coordination when env vars are present.

    Single-host multi-chip needs no rendezvous (jax sees all local chips);
    multi-host uses jax.distributed (coordinator address from
    PADDLE_MASTER / MASTER_ADDR env, paddle-launch-style env parsing —
    `launch/context/__init__.py`)."""
    global _initialized
    if _initialized:
        return ParallelEnv()
    coord = os.environ.get("MASTER_ADDR") or os.environ.get("PADDLE_MASTER")
    n_nodes = int(os.environ.get("PADDLE_NNODES",
                                 os.environ.get("WORLD_SIZE_NODES", "1")))
    already = False
    try:
        from jax._src import distributed as _jd
        already = _jd.global_state.client is not None
    except Exception:
        pass
    if coord and n_nodes > 1 and not already:
        # NOTE: importing paddle_tpu initialises the XLA backend, after
        # which jax.distributed.initialize refuses to run — multi-process
        # programs must call jax.distributed.initialize (with
        # jax_cpu_collectives_implementation="gloo" on CPU) BEFORE the
        # import; this path covers launcher-driven runs where the env is
        # set and nothing touched jax yet.
        port = os.environ.get("MASTER_PORT", "8476")
        pid = int(os.environ.get("PADDLE_NODE_RANK",
                                 os.environ.get("NODE_RANK", "0")))
        try:
            # CPU multi-process collectives need the gloo implementation
            # (the TestDistBase-style localhost two-rank tests)
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        except Exception:
            pass
        jax.distributed.initialize(
            coordinator_address=f"{coord}:{port}",
            num_processes=n_nodes, process_id=pid)
    _initialized = True
    return ParallelEnv()


def get_rank(group=None):
    """Process-level rank. Under single-controller SPMD this is the jax
    process index (0 on one host)."""
    try:
        return jax.process_index()
    except Exception:
        return 0


def get_world_size(group=None):
    """Number of devices participating in data parallelism by default."""
    if group is not None:
        return group.nranks
    try:
        return jax.device_count()
    except Exception:
        return 1


def device_count():
    return jax.device_count()


def is_initialized():
    return _initialized


def global_mesh(axes=None):
    """The framework-wide device mesh.

    axes: dict name->size (ordered), e.g. {"dp":2, "pp":2, "mp":2}.
    Defaults to a pure-dp mesh over all devices. Cached per shape."""
    if axes is None:
        axes = {"dp": jax.device_count()}
    key = tuple(axes.items())
    if key not in _mesh_cache:
        names = tuple(axes.keys())
        sizes = tuple(axes.values())
        n = int(np.prod(sizes))
        devs = np.array(jax.devices()[:n]).reshape(sizes)
        _mesh_cache[key] = jax.sharding.Mesh(devs, names)
    return _mesh_cache[key]


def barrier(group=None):
    """Host barrier: a tiny psum over all devices forces a sync point."""
    import jax.numpy as jnp
    x = jnp.ones((jax.device_count(),))
    jax.block_until_ready(
        jax.pmap(lambda v: jax.lax.psum(v, "i"), axis_name="i")(x))
