"""Elastic training manager.

Parity: `python/paddle/distributed/fleet/elastic/manager.py:127`
(`ElasticManager`: etcd registration :229, watch/scale callbacks :244,
fault-tolerant restart via the launcher).

TPU-native scope: within a slice, chip failure kills the whole SPMD
program — elasticity happens at the JOB level: a watchdog restarts the
training process and the program resumes from the latest (orbax) sharded
checkpoint. This manager implements that restart loop with a file-based
heartbeat/KV (no etcd in-image); the etcd transport can be slotted in via
the same Store interface.
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time


class FileStore:
    """KV + heartbeat store on a shared filesystem (etcd stand-in)."""

    def __init__(self, root):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def put(self, key, value):
        # atomic write: a concurrent alive_nodes() reader must never see a
        # truncated file; the dot prefix keeps in-flight temps out of the
        # heartbeat_* directory listing
        path = os.path.join(self.root, key)
        tmp = os.path.join(self.root, f".{key}.tmp{os.getpid()}")
        with open(tmp, "w") as f:
            json.dump(value, f)
        os.replace(tmp, path)

    def get(self, key, default=None):
        p = os.path.join(self.root, key)
        if not os.path.exists(p):
            return default
        try:
            with open(p) as f:
                return json.load(f)
        except (json.JSONDecodeError, OSError):
            return default

    def heartbeat(self, node_id):
        self.put(f"heartbeat_{node_id}", {"ts": time.time()})

    def alive_nodes(self, timeout=30.0):
        now = time.time()
        out = []
        for f in os.listdir(self.root):
            if f.startswith("heartbeat_") and ".tmp" not in f:
                hb = self.get(f)
                if hb and now - hb["ts"] < timeout:
                    out.append(f[len("heartbeat_"):])
        return sorted(out)


class KVMasterServer:
    """TCP KV master (the launcher master.py HTTP/etcd-server role): a
    json-line protocol over one listening socket. Second Store transport
    proving the FileStore seam is real."""

    def __init__(self, host="127.0.0.1", port=0):
        import socketserver
        import threading

        kv = {}
        lock = threading.Lock()

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                for line in self.rfile:
                    try:
                        req = json.loads(line)
                    except json.JSONDecodeError:
                        break
                    with lock:
                        if req["op"] == "put":
                            kv[req["key"]] = req["value"]
                            resp = {"ok": True}
                        elif req["op"] == "get":
                            resp = {"ok": True,
                                    "value": kv.get(req["key"])}
                        elif req["op"] == "list":
                            pfx = req.get("prefix", "")
                            resp = {"ok": True,
                                    "items": {k: v for k, v in kv.items()
                                              if k.startswith(pfx)}}
                        else:
                            resp = {"ok": False}
                    self.wfile.write((json.dumps(resp) + "\n").encode())
                    self.wfile.flush()

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()


class TcpStore:
    """Store client with the same interface as FileStore, over a
    KVMasterServer (PADDLE_ELASTIC_STORE=tcp://host:port)."""

    def __init__(self, host, port):
        import socket
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=30)
        self._rfile = self._sock.makefile("r")

    def _call(self, req):
        self._sock.sendall((json.dumps(req) + "\n").encode())
        return json.loads(self._rfile.readline())

    def put(self, key, value):
        self._call({"op": "put", "key": key, "value": value})

    def get(self, key, default=None):
        resp = self._call({"op": "get", "key": key})
        v = resp.get("value")
        return default if v is None else v

    def heartbeat(self, node_id):
        self.put(f"heartbeat_{node_id}", {"ts": time.time()})

    def alive_nodes(self, timeout=30.0):
        now = time.time()
        items = self._call({"op": "list",
                            "prefix": "heartbeat_"}).get("items", {})
        return sorted(k[len("heartbeat_"):] for k, v in items.items()
                      if v and now - v["ts"] < timeout)


def make_store(spec):
    """'tcp://host:port' -> TcpStore; anything else -> FileStore root."""
    if spec.startswith("tcp://"):
        host, port = spec[len("tcp://"):].rsplit(":", 1)
        return TcpStore(host, port)
    return FileStore(spec)


class ElasticManager:
    def __init__(self, args=None, store_root=None, max_restarts=3,
                 heartbeat_interval=5.0):
        self.store = make_store(store_root or
                                os.environ.get("PADDLE_ELASTIC_STORE",
                                               "/tmp/paddle_tpu_elastic"))
        self.max_restarts = max_restarts
        self.heartbeat_interval = heartbeat_interval
        self.node_id = os.environ.get("PADDLE_NODE_RANK", "0")
        self.restarts = 0

    def register(self):
        """manager.py:229 parity: announce this node."""
        self.store.heartbeat(self.node_id)
        self.store.put(f"node_{self.node_id}",
                       {"pid": os.getpid(), "restarts": self.restarts})

    def watch(self):
        return self.store.alive_nodes(timeout=self.heartbeat_interval * 4)

    def run(self, cmd):
        """Supervise `cmd` (the training script); restart on failure up to
        max_restarts (the launcher watchdog capability)."""
        while True:
            self.register()
            proc = subprocess.Popen(cmd)
            while proc.poll() is None:
                self.store.heartbeat(self.node_id)
                time.sleep(self.heartbeat_interval)
            if proc.returncode == 0:
                return 0
            self.restarts += 1
            if self.restarts > self.max_restarts:
                return proc.returncode
            sys.stderr.write(
                f"[elastic] training exited {proc.returncode}; "
                f"restart {self.restarts}/{self.max_restarts}\n")
