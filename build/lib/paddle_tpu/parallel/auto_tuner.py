"""Auto-parallel cost model + parallel-strategy tuner.

Parity: `python/paddle/distributed/auto_parallel/cost_model.py` (comp/comm
cost graph simulation) and `auto_parallel/tuner/` (parallel-strategy
search). TPU-native re-design: instead of simulating a serialized Program
op-graph, the model prices a transformer-family training step analytically
from the hardware roofline —

  comp  = step FLOPs / (MXU peak x efficiency)
  comm  = bytes moved per collective / ICI bandwidth  (ring allreduce =
          2 (n-1)/n x bytes, all_gather/reduce_scatter = (n-1)/n x bytes)
  pp    = bubble factor (pp-1)/(M + pp - 1) on the compute term
  mem   = params + grads + optimizer state (/ zero shard factor)
          + activations (/ pp mp, x remat factor); configs over the HBM
          budget are infeasible

and the tuner brute-force scores every (dp, mp, pp, zero, micro) mesh
factorization — the search space is tiny (divisors of n_devices), so
beam search is unnecessary on TPU pods.
"""
from __future__ import annotations

import dataclasses
import itertools
from typing import Optional


@dataclasses.dataclass
class ClusterSpec:
    """One TPU slice. Defaults are v5e-ish."""
    n_devices: int = 8
    peak_flops: float = 197e12       # bf16 per chip
    hbm_bytes: float = 16e9
    ici_bw: float = 9e10             # bytes/s per direction per link
    dcn_bw: float = 2.5e10
    mxu_efficiency: float = 0.4      # achievable fraction of peak


@dataclasses.dataclass
class ModelSpec:
    """Transformer-family training job description."""
    n_layers: int
    d_model: int
    seq_len: int
    vocab_size: int
    d_ff: int = 0
    global_batch: int = 32
    param_bytes: int = 2             # bf16 params
    grad_bytes: int = 4
    opt_state_bytes: int = 8         # Adam m+v fp32... per param elem
    master_bytes: int = 4            # fp32 master copy
    act_bytes: int = 2
    remat: bool = True

    def __post_init__(self):
        if self.d_ff == 0:
            self.d_ff = 4 * self.d_model

    @property
    def n_params(self) -> int:
        d, L = self.d_model, self.n_layers
        return (4 * d * d + 2 * d * self.d_ff) * L \
            + self.vocab_size * d + self.seq_len * d

    def step_flops(self) -> float:
        """fwd+bwd (+recompute) matmul FLOPs for one global batch."""
        toks = self.global_batch * self.seq_len
        base = 6.0 * self.n_params * toks \
            + 6.0 * self.n_layers * self.seq_len * self.d_model * toks
        if self.remat:
            base *= 4.0 / 3.0  # one extra forward
        return base


@dataclasses.dataclass
class Strategy:
    dp: int = 1
    mp: int = 1
    pp: int = 1
    micro_batches: int = 1
    zero_stage: int = 0

    def degree(self):
        return self.dp * self.mp * self.pp

    def as_hybrid_configs(self):
        return {"dp_degree": self.dp, "mp_degree": self.mp,
                "pp_degree": self.pp, "sharding_degree": 1,
                "micro_batches": self.micro_batches,
                "zero_stage": self.zero_stage}


def _ring_allreduce_time(bytes_, n, bw):
    if n <= 1 or bytes_ <= 0:
        return 0.0
    return 2.0 * (n - 1) / n * bytes_ / bw


def _shard_xfer_time(bytes_, n, bw):
    """all_gather or reduce_scatter of a full buffer over n ranks."""
    if n <= 1 or bytes_ <= 0:
        return 0.0
    return (n - 1) / n * bytes_ / bw


class CostModel:
    """Analytic step-time + memory estimate for a (model, strategy) pair."""

    def __init__(self, cluster: Optional[ClusterSpec] = None):
        self.cluster = cluster or ClusterSpec()

    # -------------------------------------------------------------- mem
    def memory_per_device(self, m: ModelSpec, s: Strategy) -> float:
        P = float(m.n_params)
        # params + grads live sharded over mp and pp always
        shard = s.mp * s.pp
        p_bytes = P * m.param_bytes / shard
        g_bytes = P * m.grad_bytes / shard
        # optimizer state (+master weights): zero>=1 additionally shards
        # over dp; zero>=2 shards grads; zero>=3 shards params too
        opt_shard = shard * (s.dp if s.zero_stage >= 1 else 1)
        o_bytes = P * (m.opt_state_bytes + m.master_bytes) / opt_shard
        if s.zero_stage >= 2:
            g_bytes /= s.dp
        if s.zero_stage >= 3:
            p_bytes /= s.dp  # params stored sharded between steps
        # activations: batch split over dp, per-microbatch live set over
        # pp stages; remat keeps ~1 residual per layer boundary
        b_local = max(m.global_batch // (s.dp * s.micro_batches), 1)
        act_per_layer = b_local * m.seq_len * m.d_model * m.act_bytes
        layers_local = max(m.n_layers // s.pp, 1)
        live_factor = 2.0 if m.remat else 14.0   # resid vs full act set
        # gpipe keeps micro_batches in flight; 1f1b keeps <= pp
        in_flight = min(s.micro_batches, s.pp)
        a_bytes = act_per_layer * layers_local * live_factor * in_flight \
            / max(s.mp, 1)
        return p_bytes + g_bytes + o_bytes + a_bytes

    # ------------------------------------------------------------- time
    def step_time(self, m: ModelSpec, s: Strategy) -> float:
        c = self.cluster
        flops = m.step_flops() / s.degree()
        comp = flops / (c.peak_flops * c.mxu_efficiency)
        # pipeline bubble stretches compute
        if s.pp > 1:
            bubble = (s.pp - 1) / max(s.micro_batches + s.pp - 1, 1)
            comp = comp / (1.0 - bubble)

        P = float(m.n_params)
        comm = 0.0
        # dp grad sync: allreduce (zero=0) or RS+AG (zero>=1) of the
        # mp/pp-local shard
        g_local = P * m.grad_bytes / (s.mp * s.pp)
        if s.zero_stage >= 1:
            comm += 2.0 * _shard_xfer_time(g_local, s.dp, c.ici_bw)
        else:
            comm += _ring_allreduce_time(g_local, s.dp, c.ici_bw)
        if s.zero_stage >= 3:
            # params stored sharded: all-gather them for fwd AND for the
            # recomputing bwd
            p_local = P * m.param_bytes / (s.mp * s.pp)
            comm += 2.0 * _shard_xfer_time(p_local, s.dp, c.ici_bw)
        # mp: 2 allreduce fwd + 2 bwd per layer of [B_local, S, d] acts
        if s.mp > 1:
            b_local = max(m.global_batch // s.dp, 1)
            act = b_local * m.seq_len * m.d_model * m.act_bytes
            layers_local = max(m.n_layers // s.pp, 1)
            comm += 4.0 * layers_local * _ring_allreduce_time(
                act, s.mp, c.ici_bw)
        # pp: p2p activation sends per microbatch tick (fwd+bwd)
        if s.pp > 1:
            b_micro = max(m.global_batch // (s.dp * s.micro_batches), 1)
            act = b_micro * m.seq_len * m.d_model * m.act_bytes
            comm += 2.0 * s.micro_batches * act / c.ici_bw
        return comp + comm


class StrategyTuner:
    """Brute-force search over mesh factorizations (the reference tuner's
    role, minus the Program rewriting — shardings here are GSPMD specs)."""

    def __init__(self, cluster: Optional[ClusterSpec] = None):
        self.cluster = cluster or ClusterSpec()
        self.cost_model = CostModel(self.cluster)

    def _factorizations(self, n):
        for dp in range(1, n + 1):
            if n % dp:
                continue
            rest = n // dp
            for mp in range(1, rest + 1):
                if rest % mp:
                    continue
                yield dp, mp, rest // mp

    def search(self, model: ModelSpec, n_devices: Optional[int] = None,
               top_k: int = 1):
        n = n_devices or self.cluster.n_devices
        scored = []
        for dp, mp, pp in self._factorizations(n):
            if model.n_layers % pp or model.global_batch % dp:
                continue
            micro_opts = {1} if pp == 1 else {
                mb for mb in (pp, 2 * pp, 4 * pp)
                if model.global_batch % (dp * mb) == 0}
            for micro in sorted(micro_opts):
                for zero in (0, 1, 2, 3):
                    s = Strategy(dp=dp, mp=mp, pp=pp,
                                 micro_batches=micro, zero_stage=zero)
                    mem = self.cost_model.memory_per_device(model, s)
                    if mem > self.cluster.hbm_bytes:
                        continue
                    t = self.cost_model.step_time(model, s)
                    # prefer simpler configs on near-ties (zero adds
                    # collectives; mp/pp add failure surface)
                    tie_break = (zero, mp, pp)
                    scored.append((t, tie_break, s, mem))
        if not scored:
            raise ValueError(
                "no feasible parallel strategy: model does not fit "
                f"{n} x {self.cluster.hbm_bytes / 1e9:.0f}GB devices")
        scored.sort(key=lambda r: (r[0], r[1]))
        if top_k == 1:
            return scored[0][2]
        return [r[2] for r in scored[:top_k]]
