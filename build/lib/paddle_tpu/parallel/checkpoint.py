"""Distributed (sharded, async) checkpointing over orbax.

Parity: SURVEY §5.4 — auto-parallel `dist_saver.py` (per-rank shards +
dist_attr, re-shard on load) and sharding stage-3 gather-before-save
(`group_sharded_utils.py`). Orbax persists each jax array with its
sharding and re-shards on restore when the mesh changes — exactly the
converter design the reference implements by hand.
"""
from __future__ import annotations

import os

import jax
import numpy as np


def _ckptr():
    import orbax.checkpoint as ocp
    return ocp


def save_sharded(state, path, async_=False):
    """state: pytree of jax arrays (params/opt_state from HybridGPT or a
    state_dict of Tensors). Writes an orbax checkpoint directory."""
    ocp = _ckptr()
    from ..core.tensor import Tensor
    state = jax.tree.map(
        lambda x: x._data if isinstance(x, Tensor) else x, state,
        is_leaf=lambda x: isinstance(x, Tensor))
    path = os.path.abspath(path)
    if async_:
        ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
    else:
        ckptr = ocp.Checkpointer(ocp.StandardCheckpointHandler())
    ckptr.save(path, state, force=True)
    if async_:
        return ckptr  # caller may wait_until_finished()
    return None


def load_sharded(path, template=None, shardings=None):
    """Restore; when `template` (pytree of arrays with target shardings)
    is given, arrays are restored directly into that sharding (re-shard on
    load)."""
    ocp = _ckptr()
    ckptr = ocp.Checkpointer(ocp.StandardCheckpointHandler())
    path = os.path.abspath(path)
    if template is not None:
        from ..core.tensor import Tensor
        template = jax.tree.map(
            lambda x: x._data if isinstance(x, Tensor) else x, template,
            is_leaf=lambda x: isinstance(x, Tensor))
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=x.sharding)
            if hasattr(x, "sharding") else x, template)
        return ckptr.restore(path, abstract)
    return ckptr.restore(path)
