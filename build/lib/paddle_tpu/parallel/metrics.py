"""Distributed training metrics.

Parity: `paddle/fluid/framework/fleet/metrics.cc` (global AUC: per-worker
bucket stats merged across all trainers) exposed as `fleet.metrics`.

TPU-native transport: trainers accumulate their local `metric.Auc`
buckets into a shared PS dense table (a naive-rule table with lr=-1 makes
`push(g)` an atomic ADD), then any trainer pulls the global buckets and
computes AUC. Single-process mode degrades to the local metric.
"""
from __future__ import annotations

import numpy as np

from ..metric import Auc


class GlobalAuc:
    def __init__(self, num_thresholds=4095, table=None):
        """`table`: a MemoryDenseTable-like (local or remote via
        PSClient) of size 2*(num_thresholds+1) with sgd_rule='naive',
        learning_rate=-1.0 so pushes accumulate."""
        self.num_thresholds = num_thresholds
        self.local = Auc(num_thresholds=num_thresholds)
        self.table = table

    @staticmethod
    def make_table(num_thresholds=4095):
        from ..ps import MemoryDenseTable
        return MemoryDenseTable(2 * (num_thresholds + 1),
                                sgd_rule="naive", learning_rate=-1.0)

    def update(self, preds, labels):
        self.local.update(preds, labels)

    def commit(self):
        """Push this worker's buckets to the shared table and reset the
        local stats (the per-pass flush in the reference).

        LIMITATION: the dense-table transport is float32, exact for
        per-bucket counts below 2^24 (~16.7M); beyond that, increments
        can be absorbed — a warning fires before precision loss (the
        reference all-reduces int64 buckets; an int64 dense table is the
        round-2 fix)."""
        if self.table is None:
            return
        import warnings
        merged = self.table.pull()
        if merged.size and merged.max() > 2 ** 23:
            warnings.warn(
                "GlobalAuc buckets approaching float32 precision limit "
                "(2^24 per bucket); counts may be lost")
        buckets = np.concatenate([self.local._stat_pos,
                                  self.local._stat_neg]).astype(np.float32)
        self.table.push(buckets)
        self.local.reset()

    def accumulate(self):
        """Global AUC over all committed buckets (+ any uncommitted local
        stats on this worker)."""
        if self.table is None:
            return self.local.accumulate()
        n = self.num_thresholds + 1
        merged = self.table.pull()
        agg = Auc(num_thresholds=self.num_thresholds)
        agg._stat_pos = merged[:n].astype(np.int64) + self.local._stat_pos
        agg._stat_neg = merged[n:].astype(np.int64) + self.local._stat_neg
        return agg.accumulate()
