"""Pipeline-parallel user API.

Parity: `python/paddle/distributed/fleet/meta_parallel/parallel_layers/
pp_layers.py:211` (`PipelineLayer`, `LayerDesc`, `SharedLayerDesc` — layer
partition across stages) and `meta_parallel/pipeline_parallel.py:34`
(`PipelineParallel` 1F1B scheduler over `pp_utils/p2p_communication.py`
NCCL send/recv).

TPU-native execution model: under single-controller SPMD the pipeline
schedule must live INSIDE a compiled step (lax.scan + ppermute over the pp
mesh axis — parallel/hybrid_gpt.py is the flagship implementation). This
module provides (a) the PipelineLayer partitioning API so reference model
code ports, and (b) a PipelineParallel wrapper whose `train_batch` runs
the REAL compiled pipeline (pipeline_schedule.CompiledPipeline: GPipe or
true-1F1B tick schedule over ppermute) when the model compiles, falling
back to eager microbatch gradient accumulation (identical gradients —
1F1B only reorders microbatch execution) otherwise.
"""
from __future__ import annotations

import numpy as np

from ..nn.layer_base import Layer
from ..nn.container import LayerList, Sequential
from ..core.tensor import Tensor
from . import env as dist_env
from .topology import get_hybrid_communicate_group


class LayerDesc:
    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_cls, forward_func=None,
                 shared_weight_attr="weight", *args, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    """Builds all stages' layers (single-controller owns every stage) and
    records the stage partition for the compiled pipeline."""

    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0,
                 **kwargs):
        super().__init__()
        self._loss_fn = loss_fn
        descs = list(layers)
        built = []
        self._shared = {}
        for i, d in enumerate(descs):
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self._shared:
                    built.append(self._shared[d.layer_name])
                    continue
                layer = d.build_layer()
                self._shared[d.layer_name] = layer
            elif isinstance(d, LayerDesc):
                layer = d.build_layer()
            elif isinstance(d, Layer):
                layer = d
            else:  # callable (e.g. lambda x: ...)
                layer = d
            built.append(layer)
        self.run_function = built
        for i, l in enumerate(built):
            if isinstance(l, Layer):
                self.add_sublayer(str(i), l)
        if num_stages is None:
            hcg = get_hybrid_communicate_group()
            num_stages = hcg.get_pipe_parallel_world_size()
        self._num_stages = max(num_stages, 1)
        n = len(built)
        per = int(np.ceil(n / self._num_stages))
        self.segment_parts = [min(i * per, n)
                              for i in range(self._num_stages + 1)]
        self.segment_parts[-1] = n

    def get_stage_layers(self, stage_id):
        lo, hi = self.segment_parts[stage_id], self.segment_parts[stage_id
                                                                  + 1]
        return self.run_function[lo:hi]

    def forward(self, x):
        for fn in self.run_function:
            x = fn(x)
        return x


class PipelineParallel(Layer):
    """fleet.distributed_model wrapper for pp topologies.

    train_batch(data, optimizer, lr_scheduler): microbatch gradient
    accumulation (1F1B-equivalent gradients), then one optimizer step.
    """

    def __init__(self, layers, hcg=None, strategy=None, schedule="1f1b"):
        super().__init__()
        self._layers = layers
        self.add_sublayer("_layers", layers)
        self._hcg = hcg or get_hybrid_communicate_group()
        pcfg = (strategy.pipeline_configs if strategy is not None
                else {"accumulate_steps": 1, "micro_batch_size": 1})
        self.accumulate_steps = pcfg.get("accumulate_steps", 1)
        self.micro_batch_size = pcfg.get("micro_batch_size", 1)
        self._schedule = schedule
        self._runner = None
        self._runner_failed = False

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def _compiled_runner(self):
        """Build the compiled pipeline (ppermute tick schedule) lazily;
        None if the model can't run it (no loss_fn / not a PipelineLayer /
        too few devices / untraceable)."""
        if self._runner is not None:
            return self._runner
        if self._runner_failed:
            return None
        try:
            from .pipeline_schedule import CompiledPipeline
            self._runner = CompiledPipeline(
                self._layers, micro_batches=self.accumulate_steps,
                schedule=self._schedule)
            return self._runner
        except Exception as e:
            import warnings
            warnings.warn(
                "compiled pipeline unavailable, falling back to eager "
                f"microbatch accumulation: {e!r}")
            self._runner_failed = True
            return None

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        inputs, labels = data
        inputs = inputs if isinstance(inputs, Tensor) else Tensor(inputs)
        labels = labels if isinstance(labels, Tensor) else Tensor(labels)
        if isinstance(self._layers, PipelineLayer) \
                and self._layers._num_stages > 1 \
                and getattr(self._layers, "_loss_fn", None) is not None:
            runner = self._compiled_runner()
            if runner is not None:
                # Guard ONLY the compiled forward/backward: a failure
                # there (trace/compile/shape) falls back to eager with
                # .grad still untouched. Optimizer/scaler errors below
                # are real user-facing errors and must propagate.
                try:
                    loss_arr, grads = runner.loss_and_grads(inputs,
                                                            labels)
                except Exception as e:
                    import warnings
                    warnings.warn(
                        "compiled pipeline step failed, falling back to "
                        f"eager microbatch accumulation: {e!r}")
                    self._runner = None
                    self._runner_failed = True  # eager fallback below
                else:
                    loss = runner.finish_batch(loss_arr, grads, optimizer,
                                               scaler)
                    if lr_scheduler is not None:
                        lr_scheduler.step()
                    return loss
        m = self.accumulate_steps
        bsz = inputs.shape[0]
        assert bsz % m == 0, "batch must divide accumulate_steps"
        mb = bsz // m
        total = None
        loss_fn = getattr(self._layers, "_loss_fn", None)
        for i in range(m):
            x = inputs[i * mb:(i + 1) * mb]
            y = labels[i * mb:(i + 1) * mb]
            out = self._layers(x)
            loss = loss_fn(out, y) if loss_fn is not None else out
            scaled = loss * (1.0 / m)
            if scaler is not None:
                scaler.scale(scaled).backward()
            else:
                scaled.backward()
            total = float(loss) if total is None else total + float(loss)
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return Tensor(np.float32(total / m))

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)
