"""Tensor-parallel layers (GSPMD tier).

Parity: `python/paddle/distributed/fleet/layers/mpu/mp_layers.py`
(`VocabParallelEmbedding:39`, `ColumnParallelLinear:155`,
`RowParallelLinear:293`, `ParallelCrossEntropy:438`) and `mp_ops.py`
(`_c_identity`, `_mp_allreduce`).

TPU-native: instead of allocating per-rank weight shards and calling NCCL
collectives by hand, these layers hold the FULL logical weight with a
`dist_spec` PartitionSpec (weight sharded over the "mp" mesh axis) and add
`with_sharding_constraint` hints in forward. When the training step is
compiled over a mesh (Model.fit / CompiledTrainStep with a placed model,
or pjit), XLA GSPMD partitions the matmuls and inserts the identity /
all-reduce collectives the reference codes by hand. On a single chip they
degrade to plain dense layers. For the fully manual (shard_map) path used
by the flagship hybrid trainer, see parallel/hybrid_gpt.py.
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..nn.layer_base import Layer
from ..nn.layers.common import Linear, Embedding
from ..nn import functional as F
from .. import ops
from ..core.tensor import Tensor
from ..core import dispatch
from . import env as dist_env
from .topology import get_hybrid_communicate_group


def _constraint(x, spec):
    """Apply a sharding constraint when tracing inside a mesh context."""
    try:
        mesh = get_hybrid_communicate_group().mesh()
        arr = x._data if isinstance(x, Tensor) else x
        if isinstance(arr, jax.core.Tracer):
            out = jax.lax.with_sharding_constraint(
                arr, NamedSharding(mesh, spec))
            if isinstance(x, Tensor):
                t = Tensor(out, stop_gradient=x.stop_gradient)
                t._grad_node, t._out_slot = x._grad_node, x._out_slot
                return t
            return out
    except Exception:
        pass
    return x


class VocabParallelEmbedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.embedding = Embedding(num_embeddings, embedding_dim,
                                   weight_attr=weight_attr)
        self.weight = self.embedding.weight
        self.weight.dist_spec = P("mp", None)
        self.weight.is_distributed = True

    def forward(self, x):
        return self.embedding(x)


class ColumnParallelLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        # reference semantics (mp_layers.py:209 `if has_bias:`): the
        # default has_bias=None means NO bias
        bias_attr = None if has_bias else False
        self.linear = Linear(in_features, out_features, weight_attr,
                             bias_attr)
        self.weight = self.linear.weight
        self.bias = self.linear.bias
        self.weight.dist_spec = P(None, "mp")
        self.weight.is_distributed = True
        if self.bias is not None:
            self.bias.dist_spec = P("mp")
            self.bias.is_distributed = True
        self.gather_output = gather_output

    def forward(self, x):
        out = self.linear(x)
        if not self.gather_output:
            out = _constraint(
                out, P(*([None] * (out.ndim - 1) + ["mp"])))
        return out


class RowParallelLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.linear = Linear(in_features, out_features, weight_attr,
                             None if has_bias else False)
        self.weight = self.linear.weight
        self.bias = self.linear.bias
        self.weight.dist_spec = P("mp", None)
        self.weight.is_distributed = True
        self.input_is_parallel = input_is_parallel

    def forward(self, x):
        return self.linear(x)


class ParallelCrossEntropy(Layer):
    """c_softmax_with_cross_entropy parity: with GSPMD the vocab-sharded
    logits reduce inside the compiled softmax; eager falls back to the
    dense kernel."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)


class TensorParallel(Layer):
    """fleet.distributed_model wrapper for pure-mp topologies (parity:
    meta_parallel/tensor_parallel.py). Placement of mp-sharded params on
    the mesh happens here."""

    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self.add_sublayer("_layers", layers)
        self._hcg = hcg or get_hybrid_communicate_group()
        place_model_on_mesh(layers, self._hcg.mesh())

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)


def place_model_on_mesh(model, mesh):
    """device_put every parameter/buffer to its dist_spec sharding
    (replicated by default) so compiled steps run SPMD over the mesh."""
    for _, p in model.named_parameters():
        spec = p.dist_spec if p.dist_spec is not None else \
            P(*([None] * p.ndim))
        p._data = jax.device_put(p._data, NamedSharding(mesh, spec))
    for _, b in model.named_buffers():
        if isinstance(b, Tensor):
            spec = b.dist_spec if b.dist_spec is not None else \
                P(*([None] * b.ndim))
            b._data = jax.device_put(b._data, NamedSharding(mesh, spec))
    return model
