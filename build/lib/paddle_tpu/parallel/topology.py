"""Hybrid-parallel topology.

Parity: `python/paddle/distributed/fleet/base/topology.py:54
CommunicateTopology` / `:140 HybridCommunicateGroup` — builds the
dp/pp/mp/sharding(/sp/ep) axes and per-axis communication groups.

TPU-native: the topology IS a `jax.sharding.Mesh` with named axes; a
"communication group" for axis X is the mesh axis name, used by shard_map
collectives inside compiled steps. Rank bookkeeping is kept for API parity
and for laying out per-rank data feeds.
"""
from __future__ import annotations

import collections
import itertools

import numpy as np
import jax

from . import env as dist_env
from .collective import Group


class CommunicateTopology:
    def __init__(self, hybrid_group_names=("data", "pipe", "sharding",
                                           "model"),
                 dims=(1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = collections.namedtuple(
            "Coordinate", self._parallel_names)
        self.world_size = int(np.prod(self._dims))
        ranges = [range(d) for d in self._dims]
        all_coords = [self.coordinate(*c)
                      for c in itertools.product(*ranges)]
        self._coord2rank = {c: i for i, c in enumerate(all_coords)}
        self._rank2coord = {i: c for c, i in self._coord2rank.items()}

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def get_rank(self, **kwargs):
        return self._coord2rank[self.coordinate(**kwargs)]

    def get_coord(self, rank):
        return self._rank2coord[rank]

    def get_axis_list(self, axis_name, index):
        axis = self._parallel_names.index(axis_name)
        ranks = [self._coord2rank[c] for c in self._coord2rank
                 if c[axis] == index]
        return sorted(ranks)

    def get_comm_list(self, axis_name):
        """All groups along `axis_name`: list of rank-lists."""
        axis = self._parallel_names.index(axis_name)
        other_axes = [i for i in range(len(self._dims)) if i != axis]
        groups = []
        for other in itertools.product(
                *[range(self._dims[i]) for i in other_axes]):
            ranks = []
            for v in range(self._dims[axis]):
                coord = [0] * len(self._dims)
                for i, o in zip(other_axes, other):
                    coord[i] = o
                coord[axis] = v
                ranks.append(self._coord2rank[self.coordinate(*coord)])
            groups.append(ranks)
        return groups


class HybridCommunicateGroup:
    """Axes order matches the reference: data, pipe, sharding, model."""

    def __init__(self, topology: CommunicateTopology, rank=None):
        self._topo = topology
        self.global_rank = rank if rank is not None else dist_env.get_rank()
        self._dp_degree = topology.get_dim("data")
        self._pp_degree = topology.get_dim("pipe")
        self._sharding_degree = topology.get_dim("sharding")
        self._mp_degree = topology.get_dim("model")
        coord = topology.get_coord(
            self.global_rank % topology.world_size)
        self._dp_rank = coord.data
        self._pp_rank = coord.pipe
        self._sharding_rank = coord.sharding
        self._mp_rank = coord.model
        # per-axis groups (rank lists) containing this rank
        self._dp_group = Group(topology.get_axis_list("data", 0), name="dp")
        self._pp_group = Group(topology.get_axis_list("pipe", 0), name="pp")
        self._mp_group = Group(topology.get_axis_list("model", 0),
                               name="mp")
        self._sharding_group = Group(
            topology.get_axis_list("sharding", 0), name="sharding")

    # --- mesh view (the TPU-native core) ---
    def mesh(self):
        """jax Mesh with axes (dp, pp, sharding, mp) collapsed of size-1
        axes."""
        axes = {}
        for name, size in (("dp", self._dp_degree),
                           ("pp", self._pp_degree),
                           ("sharding", self._sharding_degree),
                           ("mp", self._mp_degree)):
            axes[name] = size
        return dist_env.global_mesh(axes)

    # --- parity accessors (topology.py:140) ---
    def get_parallel_mode(self):
        if self._mp_degree == 1 and self._pp_degree == 1 and \
                self._sharding_degree == 1:
            return "data_parallel"
        return "hybrid_parallel"

    def get_data_parallel_rank(self):
        return self._dp_rank

    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_data_parallel_group(self):
        return self._dp_group

    def get_data_parallel_group_src_rank(self):
        return self._dp_group.ranks[0]

    def get_model_parallel_rank(self):
        return self._mp_rank

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_model_parallel_group(self):
        return self._mp_group

    def get_model_parallel_group_src_rank(self):
        return self._mp_group.ranks[0]

    def get_stage_id(self):
        return self._pp_rank

    def get_pipe_parallel_rank(self):
        return self._pp_rank

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_pipe_parallel_group(self):
        return self._pp_group

    def get_sharding_parallel_rank(self):
        return self._sharding_rank

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sharding_parallel_group(self):
        return self._sharding_group

    def get_global_rank(self):
        return self.global_rank

    def topology(self):
        return self._topo


_hcg = None


def set_hybrid_communicate_group(hcg):
    global _hcg
    _hcg = hcg


def get_hybrid_communicate_group():
    global _hcg
    if _hcg is None:
        topo = CommunicateTopology(dims=(dist_env.get_world_size(), 1, 1, 1))
        _hcg = HybridCommunicateGroup(topo)
    return _hcg
