"""paddle.DataParallel — eager data parallelism.

Parity: `python/paddle/fluid/dygraph/parallel.py:437` (`DataParallel`) +
`EagerReducer` (`paddle/fluid/distributed/collective/reducer.h:88` —
bucketed fused allreduce overlapping backward).

TPU-native: under jax's single-controller SPMD there is one python process
driving all chips, so "DataParallel" = shard the batch over the dp mesh
axis and let grads reduce inside the compiled step (GSPMD inserts the
psum; the EagerReducer's bucketing/overlap job is done by XLA's scheduler).
This wrapper therefore: (1) marks the model as dp-replicated, (2) exposes
the paddle API (scale_loss / apply_collective_grads no-ops that keep user
code working), and (3) when used with the compiled trainers, triggers
batch sharding via `shard_batch`.
"""
from __future__ import annotations

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..nn.layer_base import Layer
from ..core.tensor import Tensor
from . import env as dist_env


def shard_batch(arrays, mesh=None, axis="dp"):
    """Place host batch arrays sharded over the dp mesh axis (dim 0)."""
    mesh = mesh or dist_env.global_mesh()
    out = []
    for a in arrays:
        arr = a._data if isinstance(a, Tensor) else np.asarray(a)
        spec = P(axis, *([None] * (arr.ndim - 1)))
        out.append(jax.device_put(arr, NamedSharding(mesh, spec)))
    return out


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.add_sublayer("_layers", layers)
        self.comm_buffer_size = comm_buffer_size
        self.find_unused_parameters = find_unused_parameters
        self._nranks = dist_env.get_world_size()
        for p in layers.parameters():
            p.is_distributed = False  # replicated over dp

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        # grads average inside the compiled step (psum/mean over dp)
        return loss

    def apply_collective_grads(self):
        pass

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    @property
    def parameters_(self):
        return self._layers.parameters()

    def no_sync(self):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            yield
        return ctx()
