from .main import main

main()
