"""`python -m paddle_tpu.distributed.launch [--nnodes N] [--master ip:port]
[--rank R] script.py args...`"""
from __future__ import annotations

import argparse
import os
import runpy
import sys


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="Launch a (multi-host) TPU training job. One process "
                    "per host drives all local chips (single-controller "
                    "SPMD); multi-host coordination runs over "
                    "jax.distributed.")
    parser.add_argument("--nnodes", type=int,
                        default=int(os.environ.get("PADDLE_NNODES", "1")))
    parser.add_argument("--master", type=str,
                        default=os.environ.get("PADDLE_MASTER"))
    parser.add_argument("--rank", type=int,
                        default=int(os.environ.get("PADDLE_NODE_RANK",
                                                   "0")))
    parser.add_argument("--nproc_per_node", type=int, default=1,
                        help="accepted for reference-CLI compatibility; "
                             "ignored (chips are driven by one process)")
    parser.add_argument("--log_dir", type=str, default=None)
    parser.add_argument("script", type=str)
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    if args.nnodes > 1:
        if not args.master:
            parser.error("--master ip:port is required when --nnodes > 1")
        host, _, port = args.master.partition(":")
        os.environ["MASTER_ADDR"] = host
        os.environ["MASTER_PORT"] = port or "8476"
        os.environ["PADDLE_NNODES"] = str(args.nnodes)
        os.environ["PADDLE_NODE_RANK"] = str(args.rank)

    sys.argv = [args.script] + list(args.script_args)
    runpy.run_path(args.script, run_name="__main__")


if __name__ == "__main__":
    main()
