"""Multi-host launcher.

Parity: `python -m paddle.distributed.launch`
(`python/paddle/distributed/launch/main.py:18`, controllers
`collective.py`, `master.py`).

TPU-native: within one host, jax's single controller drives all local
chips — no per-chip process spawning (the reference forks one proc per
GPU). Across hosts, one process per host; this launcher fills the env that
`paddle_tpu.distributed.init_parallel_env` consumes
(MASTER_ADDR/MASTER_PORT/PADDLE_NNODES/PADDLE_NODE_RANK → fed to
jax.distributed.initialize) and execs the training script.
"""
from .main import main  # noqa: F401
