"""fleet — the distributed facade.

Parity: `python/paddle/distributed/fleet/fleet.py:107` (`Fleet`: init,
distributed_model, distributed_optimizer, worker/server lifecycle) +
role_maker env parsing (`fleet/base/role_maker.py`).

TPU-native: `fleet.init` builds the hybrid topology/mesh; `distributed_model`
wraps per the parallel mode (DataParallel now; PipelineParallel in
parallel/pipeline.py); `distributed_optimizer` returns a
HybridParallelOptimizer that folds dp-grad reduction/sharding into the
compiled step. PS mode (init_server/init_worker) binds to the native PS
engine (paddle_tpu/ps).
"""
from __future__ import annotations

from . import env as dist_env
from .strategy import DistributedStrategy
from .topology import (CommunicateTopology, HybridCommunicateGroup,
                       set_hybrid_communicate_group)
from .data_parallel import DataParallel


class _RoleMakerStub:
    def __init__(self, is_collective=True):
        self._is_collective = is_collective


class Fleet:
    def __init__(self):
        self._strategy = None
        self._hcg = None
        self._is_collective = True
        self._role_maker = None
        self._user_defined_optimizer = None

    # ------------------------------------------------------------- init
    def init(self, role_maker=None, is_collective=False, strategy=None,
             log_level="INFO"):
        self._is_collective = is_collective or role_maker is None
        self._role_maker = role_maker or _RoleMakerStub(is_collective)
        self._strategy = strategy or DistributedStrategy()
        hc = self._strategy.hybrid_configs
        world = dist_env.get_world_size()
        dp = hc.get("dp_degree", 1)
        mp = hc.get("mp_degree", 1)
        pp = hc.get("pp_degree", 1)
        sh = hc.get("sharding_degree", 1)
        if dp * mp * pp * sh < world and dp == 1 and mp == 1 and pp == 1:
            dp = world // (mp * pp * sh)
            hc["dp_degree"] = dp
        topo = CommunicateTopology(dims=(dp, pp, sh, mp))
        self._hcg = HybridCommunicateGroup(topo)
        set_hybrid_communicate_group(self._hcg)
        dist_env.init_parallel_env()
        return self

    def get_hybrid_communicate_group(self):
        return self._hcg

    @property
    def worker_num(self):
        return dist_env.get_world_size()

    def worker_index(self):
        return dist_env.get_rank()

    def is_first_worker(self):
        return dist_env.get_rank() == 0

    def is_worker(self):
        return True

    def is_server(self):
        return False

    def barrier_worker(self):
        dist_env.barrier()

    # ------------------------------------------------------ distributed
    def distributed_model(self, model):
        if self._hcg is None:
            self.init(is_collective=True)
        mode = self._hcg.get_parallel_mode()
        if mode == "data_parallel":
            return DataParallel(model)
        if self._hcg.get_pipe_parallel_world_size() > 1:
            from .pipeline import PipelineParallel
            return PipelineParallel(model, self._hcg, self._strategy)
        from .mp_layers import TensorParallel
        return TensorParallel(model, self._hcg, self._strategy)

    def distributed_optimizer(self, optimizer, strategy=None):
        if strategy is not None:
            self._strategy = strategy
        self._user_defined_optimizer = optimizer
        from .hybrid_optimizer import HybridParallelOptimizer
        return HybridParallelOptimizer(optimizer, self._hcg, self._strategy)

    # --------------------------------------------------------------- PS
    def init_worker(self, scopes=None):
        from ..ps.runtime import get_ps_runtime
        get_ps_runtime().init_worker()

    def init_server(self, *args, **kwargs):
        from ..ps.runtime import get_ps_runtime
        get_ps_runtime().init_server()

    def run_server(self):
        from ..ps.runtime import get_ps_runtime
        get_ps_runtime().run_server()

    def stop_worker(self):
        from ..ps.runtime import get_ps_runtime
        get_ps_runtime().stop_worker()

    def save_persistables(self, executor=None, dirname=None, main_program=None,
                          mode=0):
        from ..ps.runtime import get_ps_runtime
        get_ps_runtime().save_persistables(dirname)

    # ------------------------------------------------------------- misc
    def all_reduce(self, input, mode="sum"):
        from .collective import all_reduce as ar
        return ar(input)


fleet = Fleet()
init = fleet.init
distributed_model = fleet.distributed_model
distributed_optimizer = fleet.distributed_optimizer
