"""Vision ops — parity: `python/paddle/vision/ops.py` (nms, roi_align,
box ops; deform_conv planned)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..ops._helpers import as_tensor
from ..core import dispatch


def _nms_single(b, s, iou_threshold):
    order = np.argsort(-s)
    keep = []
    suppressed = np.zeros(len(b), bool)
    areas = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        xx1 = np.maximum(b[i, 0], b[:, 0])
        yy1 = np.maximum(b[i, 1], b[:, 1])
        xx2 = np.minimum(b[i, 2], b[:, 2])
        yy2 = np.minimum(b[i, 3], b[:, 3])
        inter = np.maximum(0, xx2 - xx1) * np.maximum(0, yy2 - yy1)
        iou = inter / np.maximum(areas[i] + areas - inter, 1e-9)
        suppressed |= iou > iou_threshold
        suppressed[i] = True
    return keep


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS (host loop — eager-only like the reference's CPU path).
    boxes [N,4] (x1,y1,x2,y2); per-category when category_idxs given.
    Returns kept indices sorted by score."""
    b = as_tensor(boxes).numpy()
    s = as_tensor(scores).numpy() if scores is not None else \
        np.arange(len(b), 0, -1, dtype=np.float32)
    if category_idxs is not None:
        cats = as_tensor(category_idxs).numpy()
        cat_list = (as_tensor(categories).numpy().tolist()
                    if categories is not None else np.unique(cats).tolist())
        keep = []
        for c in cat_list:
            idx = np.where(cats == c)[0]
            if idx.size == 0:
                continue
            kept = _nms_single(b[idx], s[idx], iou_threshold)
            keep.extend(idx[kept].tolist())
    else:
        keep = _nms_single(b, s, iou_threshold)
    keep = np.asarray(sorted(keep, key=lambda i: -s[i]), np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(keep)


def box_area(boxes):
    boxes = as_tensor(boxes)

    def _fn(b):
        return (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    return dispatch.apply("box_area", _fn, (boxes,))


def box_iou(boxes1, boxes2):
    boxes1, boxes2 = as_tensor(boxes1), as_tensor(boxes2)

    def _fn(b1, b2):
        a1 = (b1[:, 2] - b1[:, 0]) * (b1[:, 3] - b1[:, 1])
        a2 = (b2[:, 2] - b2[:, 0]) * (b2[:, 3] - b2[:, 1])
        lt = jnp.maximum(b1[:, None, :2], b2[None, :, :2])
        rb = jnp.minimum(b1[:, None, 2:], b2[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / jnp.maximum(a1[:, None] + a2[None, :] - inter,
                                   1e-9)
    return dispatch.apply("box_iou", _fn, (boxes1, boxes2))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """RoIAlign via bilinear grid sampling (XLA gather).
    x [N,C,H,W]; boxes [R,4]; boxes_num [N]."""
    x, boxes = as_tensor(x), as_tensor(boxes)
    boxes_num = as_tensor(boxes_num)
    oh, ow = (output_size if isinstance(output_size, (tuple, list))
              else (output_size, output_size))

    def _fn(img, bxs, bn):
        R = bxs.shape[0]
        C, H, W = img.shape[1], img.shape[2], img.shape[3]
        # map each roi to its batch image
        batch_idx = jnp.repeat(jnp.arange(bn.shape[0]), bn,
                               total_repeat_length=R)
        off = 0.5 if aligned else 0.0
        x1 = bxs[:, 0] * spatial_scale - off
        y1 = bxs[:, 1] * spatial_scale - off
        x2 = bxs[:, 2] * spatial_scale - off
        y2 = bxs[:, 3] * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1e-3)
        rh = jnp.maximum(y2 - y1, 1e-3)
        ys = y1[:, None] + (jnp.arange(oh) + 0.5)[None, :] * \
            (rh[:, None] / oh)                       # [R, oh]
        xs = x1[:, None] + (jnp.arange(ow) + 0.5)[None, :] * \
            (rw[:, None] / ow)                       # [R, ow]

        def bilinear(r):
            im = img[batch_idx[r]]                   # [C,H,W]
            yy = jnp.clip(ys[r], 0, H - 1)
            xx = jnp.clip(xs[r], 0, W - 1)
            y0 = jnp.floor(yy).astype(jnp.int32)
            x0 = jnp.floor(xx).astype(jnp.int32)
            y1_ = jnp.minimum(y0 + 1, H - 1)
            x1_ = jnp.minimum(x0 + 1, W - 1)
            wy = yy - y0
            wx = xx - x0
            # gather 4 corners: [C, oh, ow]
            def g(yi, xi):
                return im[:, yi][:, :, xi]
            out = (g(y0, x0) * (1 - wy)[None, :, None]
                   * (1 - wx)[None, None, :]
                   + g(y1_, x0) * wy[None, :, None]
                   * (1 - wx)[None, None, :]
                   + g(y0, x1_) * (1 - wy)[None, :, None]
                   * wx[None, None, :]
                   + g(y1_, x1_) * wy[None, :, None]
                   * wx[None, None, :])
            return out
        return jax.vmap(bilinear)(jnp.arange(R))
    return dispatch.apply("roi_align", _fn, (x, boxes, boxes_num))
