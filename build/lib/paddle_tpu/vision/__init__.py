"""paddle_tpu.vision — `python/paddle/vision/` parity."""
from . import models  # noqa: F401
from . import datasets  # noqa: F401
from . import transforms  # noqa: F401
from . import ops  # noqa: F401
from .models import LeNet, ResNet, resnet18, resnet50  # noqa: F401
