"""Minimal numpy transforms — parity: `python/paddle/vision/transforms/`.

Operate on numpy CHW float arrays (the DataLoader host path).
"""
from __future__ import annotations

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class ToTensor:
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img, dtype=np.float32)
        if arr.ndim == 2:
            arr = arr[None]
        elif arr.ndim == 3 and arr.shape[-1] in (1, 3, 4) and \
                self.data_format == "CHW" and arr.shape[0] not in (1, 3, 4):
            arr = arr.transpose(2, 0, 1)
        if arr.max() > 1.5:
            arr = arr / 255.0
        return arr


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32).reshape(-1, 1, 1)
        self.std = np.asarray(std, np.float32).reshape(-1, 1, 1)

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        if arr.ndim == 2:
            arr = arr[None]
        return (arr - self.mean) / self.std


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        c, h, w = arr.shape
        th, tw = self.size
        ys = (np.arange(th) * (h / th)).astype(np.int64).clip(0, h - 1)
        xs = (np.arange(tw) * (w / tw)).astype(np.int64).clip(0, w - 1)
        return arr[:, ys][:, :, xs]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.ascontiguousarray(img[..., ::-1])
        return img


class RandomCrop:
    def __init__(self, size, padding=0):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def __call__(self, img):
        arr = np.asarray(img)
        if self.padding:
            arr = np.pad(arr, ((0, 0), (self.padding, self.padding),
                               (self.padding, self.padding)))
        c, h, w = arr.shape
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return arr[:, i:i + th, j:j + tw]


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        arr = np.asarray(img)
        c, h, w = arr.shape
        th, tw = self.size
        i = (h - th) // 2
        j = (w - tw) // 2
        return arr[:, i:i + th, j:j + tw]


class BaseTransform:
    """`transforms.BaseTransform` parity: subclass and implement
    `_apply_image` (and `_apply_<key>` for other keys); inputs are
    dispatched per key — keys without a matching `_apply_<key>` pass
    through untouched."""

    def __init__(self, keys=None):
        self.keys = keys or ("image",)

    def _apply_image(self, img):
        raise NotImplementedError

    def __call__(self, inputs):
        if isinstance(inputs, (list, tuple)):
            out = []
            for key, item in zip(self.keys, inputs):
                fn = getattr(self, f"_apply_{key}", None)
                out.append(fn(item) if fn is not None else item)
            out.extend(inputs[len(self.keys):])
            return type(inputs)(out)
        return self._apply_image(inputs)


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = tuple(order)

    def __call__(self, img):
        return np.asarray(img).transpose(self.order)


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant"):
        if isinstance(padding, int):
            padding = (padding,) * 4
        elif len(padding) == 2:
            padding = (padding[0], padding[1], padding[0], padding[1])
        self.padding = padding  # (left, top, right, bottom)
        self.fill = fill
        self.mode = padding_mode

    def __call__(self, img):
        arr = np.asarray(img)
        l, t, r, b = self.padding
        if self.mode == "constant":
            return np.pad(arr, ((0, 0), (t, b), (l, r)),
                          constant_values=self.fill)
        return np.pad(arr, ((0, 0), (t, b), (l, r)), mode=self.mode)


class RandomVerticalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.ascontiguousarray(np.asarray(img)[..., ::-1, :])
        return img


class Grayscale:
    def __init__(self, num_output_channels=1):
        self.n = num_output_channels

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        gray = (0.299 * arr[0] + 0.587 * arr[1] + 0.114 * arr[2])[None]
        return np.repeat(gray, self.n, axis=0)


class BrightnessTransform:
    def __init__(self, value):
        self.value = float(value)

    def __call__(self, img):
        if self.value == 0:
            return img
        f = np.random.uniform(max(0.0, 1.0 - self.value),
                      1.0 + self.value)
        return np.clip(np.asarray(img, np.float32) * f, 0,
                       255.0 if np.asarray(img).max() > 1.5 else 1.0)


class ContrastTransform:
    def __init__(self, value):
        self.value = float(value)

    def __call__(self, img):
        if self.value == 0:
            return img
        arr = np.asarray(img, np.float32)
        f = np.random.uniform(max(0.0, 1.0 - self.value),
                      1.0 + self.value)
        mean = arr.mean()
        hi = 255.0 if arr.max() > 1.5 else 1.0
        return np.clip(mean + (arr - mean) * f, 0, hi)


class SaturationTransform:
    def __init__(self, value):
        self.value = float(value)

    def __call__(self, img):
        if self.value == 0:
            return img
        arr = np.asarray(img, np.float32)
        gray = (0.299 * arr[0] + 0.587 * arr[1]
                + 0.114 * arr[2])[None]
        f = np.random.uniform(max(0.0, 1.0 - self.value),
                      1.0 + self.value)
        hi = 255.0 if arr.max() > 1.5 else 1.0
        return np.clip(gray + (arr - gray) * f, 0, hi)


class HueTransform:
    """Approximate hue shift by rotating chroma channels in YIQ space."""

    def __init__(self, value):
        self.value = float(value)  # fraction of the hue circle (<=0.5)

    def __call__(self, img):
        if self.value == 0:
            return img
        arr = np.asarray(img, np.float32)
        hi = 255.0 if arr.max() > 1.5 else 1.0
        theta = np.random.uniform(-self.value, self.value) * 2 * np.pi
        r, g, b = arr[0] / hi, arr[1] / hi, arr[2] / hi
        y = 0.299 * r + 0.587 * g + 0.114 * b
        i = 0.596 * r - 0.274 * g - 0.322 * b
        q = 0.211 * r - 0.523 * g + 0.312 * b
        i2 = i * np.cos(theta) - q * np.sin(theta)
        q2 = i * np.sin(theta) + q * np.cos(theta)
        r2 = y + 0.956 * i2 + 0.621 * q2
        g2 = y - 0.272 * i2 - 0.647 * q2
        b2 = y - 1.106 * i2 + 1.703 * q2
        return np.clip(np.stack([r2, g2, b2]) * hi, 0, hi)


class ColorJitter:
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        self.ts = [BrightnessTransform(brightness),
                   ContrastTransform(contrast),
                   SaturationTransform(saturation), HueTransform(hue)]

    def __call__(self, img):
        order = np.random.permutation(len(self.ts))
        for i in order:
            img = self.ts[i](img)
        return img


class RandomResizedCrop:
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self._resize = Resize(self.size, interpolation)

    def __call__(self, img):
        arr = np.asarray(img)
        c, h, w = arr.shape
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if cw <= w and ch <= h:
                y = np.random.randint(0, h - ch + 1)
                x = np.random.randint(0, w - cw + 1)
                return self._resize(arr[:, y:y + ch, x:x + cw])
        return self._resize(arr)  # fallback: whole image


def _affine_grid_sample(arr, mat, fill=0.0):
    """Nearest-neighbour inverse-warp by a 2x3 affine matrix (host)."""
    c, h, w = arr.shape
    ys, xs = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    # centre-origin coordinates
    xc, yc = xs - (w - 1) / 2.0, ys - (h - 1) / 2.0
    sx = mat[0, 0] * xc + mat[0, 1] * yc + mat[0, 2] + (w - 1) / 2.0
    sy = mat[1, 0] * xc + mat[1, 1] * yc + mat[1, 2] + (h - 1) / 2.0
    sxr = np.round(sx).astype(np.int64)
    syr = np.round(sy).astype(np.int64)
    valid = (sxr >= 0) & (sxr < w) & (syr >= 0) & (syr < h)
    out = np.full_like(arr, fill, dtype=np.float32)
    out[:, valid] = arr[:, syr[valid], sxr[valid]]
    return out


class RandomRotation:
    def __init__(self, degrees, fill=0):
        if isinstance(degrees, (int, float)):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.fill = fill

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        a = np.deg2rad(np.random.uniform(*self.degrees))
        # inverse rotation matrix
        mat = np.array([[np.cos(a), np.sin(a), 0],
                        [-np.sin(a), np.cos(a), 0]], np.float32)
        return _affine_grid_sample(arr, mat, self.fill)


class RandomAffine:
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 fill=0):
        if isinstance(degrees, (int, float)):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.fill = fill

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        c, h, w = arr.shape
        a = np.deg2rad(np.random.uniform(*self.degrees))
        s = np.random.uniform(*self.scale) if self.scale else 1.0
        tx = ty = 0.0
        if self.translate:
            tx = np.random.uniform(-self.translate[0],
                                   self.translate[0]) * w
            ty = np.random.uniform(-self.translate[1],
                                   self.translate[1]) * h
        if isinstance(self.shear, (list, tuple)):
            sh = np.deg2rad(np.random.uniform(self.shear[0],
                                              self.shear[1]))
        elif isinstance(self.shear, (int, float)) and self.shear:
            sh = np.deg2rad(np.random.uniform(-self.shear, self.shear))
        else:
            sh = 0.0
        # inverse of rotate+scale+shear+translate
        cs, sn = np.cos(a), np.sin(a)
        fwd = np.array([[s * cs, s * (-sn + np.tan(sh) * cs)],
                        [s * sn, s * (cs + np.tan(sh) * sn)]], np.float32)
        inv = np.linalg.inv(fwd)
        mat = np.zeros((2, 3), np.float32)
        mat[:, :2] = inv
        mat[:, 2] = -inv @ np.array([tx, ty], np.float32)
        return _affine_grid_sample(arr, mat, self.fill)


class RandomPerspective:
    def __init__(self, prob=0.5, distortion_scale=0.5, fill=0):
        self.prob = prob
        self.scale = distortion_scale
        self.fill = fill

    def __call__(self, img):
        if np.random.rand() >= self.prob:
            return img
        arr = np.asarray(img, np.float32)
        c, h, w = arr.shape
        d = self.scale
        # random shifts of the four corners -> projective transform
        src = np.array([[0, 0], [w - 1, 0], [w - 1, h - 1], [0, h - 1]],
                       np.float32)
        jitter = np.random.uniform(0, d, (4, 2)).astype(np.float32) \
            * np.array([w / 2, h / 2], np.float32)
        signs = np.array([[1, 1], [-1, 1], [-1, -1], [1, -1]], np.float32)
        dst = src + jitter * signs
        # solve the 8-dof homography dst -> src (inverse warp)
        A, bvec = [], []
        for (xs_, ys_), (xd, yd) in zip(src, dst):
            A.append([xd, yd, 1, 0, 0, 0, -xs_ * xd, -xs_ * yd])
            bvec.append(xs_)
            A.append([0, 0, 0, xd, yd, 1, -ys_ * xd, -ys_ * yd])
            bvec.append(ys_)
        hvec = np.linalg.solve(np.asarray(A, np.float32),
                               np.asarray(bvec, np.float32))
        H = np.append(hvec, 1.0).reshape(3, 3)
        ys, xs = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
        denom = H[2, 0] * xs + H[2, 1] * ys + H[2, 2]
        sx = (H[0, 0] * xs + H[0, 1] * ys + H[0, 2]) / denom
        sy = (H[1, 0] * xs + H[1, 1] * ys + H[1, 2]) / denom
        sxr, syr = np.round(sx).astype(np.int64), \
            np.round(sy).astype(np.int64)
        valid = (sxr >= 0) & (sxr < w) & (syr >= 0) & (syr < h)
        out = np.full_like(arr, self.fill, dtype=np.float32)
        out[:, valid] = arr[:, syr[valid], sxr[valid]]
        return out


class RandomErasing:
    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value

    def __call__(self, img):
        if np.random.rand() >= self.prob:
            return img
        arr = np.array(img, np.float32)
        c, h, w = arr.shape
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.random.uniform(*self.ratio)
            eh = int(round(np.sqrt(target / ar)))
            ew = int(round(np.sqrt(target * ar)))
            if eh < h and ew < w:
                y = np.random.randint(0, h - eh)
                x = np.random.randint(0, w - ew)
                arr[:, y:y + eh, x:x + ew] = self.value
                return arr
        return arr
