"""ShuffleNetV2 — parity: `python/paddle/vision/models/shufflenetv2.py`.
Channel-split + depthwise units with channel shuffle between groups."""
from __future__ import annotations

from ... import nn
from ...ops.manipulation import concat, flatten, reshape, transpose


def _channel_shuffle(x, groups):
    n, c, h, w = x.shape
    x = reshape(x, [n, groups, c // groups, h, w])
    x = transpose(x, [0, 2, 1, 3, 4])
    return reshape(x, [n, c, h, w])


def _conv_bn_relu(inp, oup, k, stride=1, groups=1, relu=True,
                  act="relu"):
    pad = k // 2
    layers = [nn.Conv2D(inp, oup, k, stride=stride, padding=pad,
                        groups=groups, bias_attr=False),
              nn.BatchNorm2D(oup)]
    if relu:
        layers.append(nn.Swish() if act == "swish" else nn.ReLU())
    return nn.Sequential(*layers)


class _InvertedResidual(nn.Layer):
    def __init__(self, inp, oup, stride, act="relu"):
        super().__init__()
        self.stride = stride
        branch = oup // 2
        if stride == 1:
            self.branch2 = nn.Sequential(
                _conv_bn_relu(branch, branch, 1, act=act),
                _conv_bn_relu(branch, branch, 3, stride, groups=branch,
                              relu=False),
                _conv_bn_relu(branch, branch, 1, act=act))
        else:
            self.branch1 = nn.Sequential(
                _conv_bn_relu(inp, inp, 3, stride, groups=inp,
                              relu=False),
                _conv_bn_relu(inp, branch, 1, act=act))
            self.branch2 = nn.Sequential(
                _conv_bn_relu(inp, branch, 1, act=act),
                _conv_bn_relu(branch, branch, 3, stride, groups=branch,
                              relu=False),
                _conv_bn_relu(branch, branch, 1, act=act))

    def forward(self, x):
        if self.stride == 1:
            c = x.shape[1] // 2
            x1, x2 = x[:, :c], x[:, c:]
            out = concat([x1, self.branch2(x2)], axis=1)
        else:
            out = concat([self.branch1(x), self.branch2(x)], axis=1)
        return _channel_shuffle(out, 2)


_STAGE_OUT = {
    0.25: (24, 24, 48, 96, 512),
    0.33: (24, 32, 64, 128, 512),
    0.5: (24, 48, 96, 192, 1024),
    1.0: (24, 116, 232, 464, 1024),
    1.5: (24, 176, 352, 704, 1024),
    2.0: (24, 244, 488, 976, 2048),
}
_REPEATS = (4, 8, 4)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        chs = _STAGE_OUT[scale]
        self.conv1 = _conv_bn_relu(3, chs[0], 3, stride=2, act=act)
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        stages = []
        inp = chs[0]
        for stage_i, rep in enumerate(_REPEATS):
            oup = chs[stage_i + 1]
            units = [_InvertedResidual(inp, oup, 2, act=act)]
            units += [_InvertedResidual(oup, oup, 1, act=act)
                      for _ in range(rep - 1)]
            stages.append(nn.Sequential(*units))
            inp = oup
        self.stages = nn.Sequential(*stages)
        self.conv5 = _conv_bn_relu(inp, chs[4], 1, act=act)
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(chs[4], num_classes)

    def forward(self, x):
        x = self.conv5(self.stages(self.maxpool(self.conv1(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(flatten(x, 1))
        return x


def shufflenet_v2_x0_25(**kw):
    return ShuffleNetV2(scale=0.25, **kw)


def shufflenet_v2_x0_33(**kw):
    return ShuffleNetV2(scale=0.33, **kw)


def shufflenet_v2_x0_5(**kw):
    return ShuffleNetV2(scale=0.5, **kw)


def shufflenet_v2_x1_0(**kw):
    return ShuffleNetV2(scale=1.0, **kw)


def shufflenet_v2_x1_5(**kw):
    return ShuffleNetV2(scale=1.5, **kw)


def shufflenet_v2_x2_0(**kw):
    return ShuffleNetV2(scale=2.0, **kw)
