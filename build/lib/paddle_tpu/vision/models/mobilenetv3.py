"""MobileNetV3 (small/large) — parity:
`python/paddle/vision/models/mobilenetv3.py`: inverted residuals with
squeeze-excitation and hardswish."""
from __future__ import annotations

from ... import nn
from ...ops.manipulation import flatten


def _make_divisible(v, divisor=8):
    new_v = max(divisor, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


def _cbn(inp, oup, k, stride=1, groups=1, act=None):
    layers = [nn.Conv2D(inp, oup, k, stride=stride, padding=k // 2,
                        groups=groups, bias_attr=False),
              nn.BatchNorm2D(oup)]
    if act == "relu":
        layers.append(nn.ReLU())
    elif act == "hardswish":
        layers.append(nn.Hardswish())
    return nn.Sequential(*layers)


class _SE(nn.Layer):
    def __init__(self, ch, reduction=4):
        super().__init__()
        mid = _make_divisible(ch // reduction)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(ch, mid, 1)
        self.relu = nn.ReLU()
        self.fc2 = nn.Conv2D(mid, ch, 1)
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class _Block(nn.Layer):
    def __init__(self, inp, exp, oup, k, stride, se, act):
        super().__init__()
        self.use_res = stride == 1 and inp == oup
        layers = []
        if exp != inp:
            layers.append(_cbn(inp, exp, 1, act=act))
        layers.append(_cbn(exp, exp, k, stride=stride, groups=exp,
                           act=act))
        if se:
            layers.append(_SE(exp))
        layers.append(_cbn(exp, oup, 1, act=None))
        self.body = nn.Sequential(*layers)

    def forward(self, x):
        y = self.body(x)
        return x + y if self.use_res else y


# (kernel, exp, out, SE, act, stride)
_LARGE = [
    (3, 16, 16, False, "relu", 1),
    (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1),
    (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1),
    (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2),
    (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1),
    (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2),
    (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1),
]
_SMALL = [
    (3, 16, 16, True, "relu", 2),
    (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1),
    (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1),
    (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1),
    (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2),
    (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1),
]


class MobileNetV3(nn.Layer):
    def __init__(self, config, last_ch, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        inp = _make_divisible(16 * scale)
        self.stem = _cbn(3, inp, 3, stride=2, act="hardswish")
        blocks = []
        for k, exp, out, se, act, stride in config:
            e = _make_divisible(exp * scale)
            o = _make_divisible(out * scale)
            blocks.append(_Block(inp, e, o, k, stride, se, act))
            inp = o
        self.blocks = nn.Sequential(*blocks)
        # tail width = last block's expansion width (no identity check:
        # callers may pass modified configs)
        last_exp = _make_divisible(config[-1][1] * scale)
        self.tail = _cbn(inp, last_exp, 1, act="hardswish")
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(last_exp, last_ch), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_ch, num_classes))

    def forward(self, x):
        x = self.tail(self.blocks(self.stem(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(flatten(x, 1))
        return x


class MobileNetV3Large(MobileNetV3):
    def __init__(self, scale=1.0, **kw):
        super().__init__(_LARGE, 1280, scale=scale, **kw)


class MobileNetV3Small(MobileNetV3):
    def __init__(self, scale=1.0, **kw):
        super().__init__(_SMALL, 1024, scale=scale, **kw)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Large(scale=scale, **kwargs)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Small(scale=scale, **kwargs)
