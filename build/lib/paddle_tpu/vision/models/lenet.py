"""LeNet — parity: `python/paddle/vision/models/lenet.py` (BASELINE config 1)."""
from __future__ import annotations

from ... import nn


class LeNet(nn.Layer):
    def __init__(self, num_classes=10):
        super().__init__()
        self.num_classes = num_classes
        self.features = nn.Sequential(
            nn.Conv2D(1, 6, 3, stride=1, padding=1),
            nn.ReLU(),
            nn.MaxPool2D(2, 2),
            nn.Conv2D(6, 16, 5, stride=1, padding=0),
            nn.ReLU(),
            nn.MaxPool2D(2, 2),
        )
        if num_classes > 0:
            self.fc = nn.Sequential(
                nn.Linear(400, 120),
                nn.Linear(120, 84),
                nn.Linear(84, num_classes),
            )

    def forward(self, inputs):
        x = self.features(inputs)
        if self.num_classes > 0:
            from ...ops.manipulation import flatten
            x = flatten(x, 1)
            x = self.fc(x)
        return x
