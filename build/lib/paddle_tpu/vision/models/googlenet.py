"""GoogLeNet (Inception v1) — parity:
`python/paddle/vision/models/googlenet.py` (main head + two auxiliary
classifier heads in train mode)."""
from __future__ import annotations

from ... import nn
from ...ops.manipulation import concat, flatten


def _conv_relu(inp, oup, k, stride=1, padding=0):
    return nn.Sequential(
        nn.Conv2D(inp, oup, k, stride=stride, padding=padding),
        nn.ReLU())


class _Inception(nn.Layer):
    def __init__(self, inp, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = _conv_relu(inp, c1, 1)
        self.b2 = nn.Sequential(_conv_relu(inp, c3r, 1),
                                _conv_relu(c3r, c3, 3, padding=1))
        self.b3 = nn.Sequential(_conv_relu(inp, c5r, 1),
                                _conv_relu(c5r, c5, 5, padding=2))
        self.b4 = nn.Sequential(nn.MaxPool2D(3, stride=1, padding=1),
                                _conv_relu(inp, proj, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)],
                      axis=1)


class _AuxHead(nn.Layer):
    def __init__(self, inp, num_classes):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D(4)
        self.conv = _conv_relu(inp, 128, 1)
        self.fc1 = nn.Linear(128 * 16, 1024)
        self.relu = nn.ReLU()
        self.drop = nn.Dropout(0.7)
        self.fc2 = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = flatten(self.conv(self.pool(x)), 1)
        return self.fc2(self.drop(self.relu(self.fc1(x))))


class GoogLeNet(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _conv_relu(3, 64, 7, stride=2, padding=3),
            nn.MaxPool2D(3, stride=2, padding=1),
            _conv_relu(64, 64, 1),
            _conv_relu(64, 192, 3, padding=1),
            nn.MaxPool2D(3, stride=2, padding=1))
        self.ince3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.ince3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, stride=2, padding=1)
        self.ince4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.ince4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.ince4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.ince4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.ince4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, stride=2, padding=1)
        self.ince5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.ince5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.pool5 = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.drop = nn.Dropout(0.4)
            self.fc = nn.Linear(1024, num_classes)
            self.aux1 = _AuxHead(512, num_classes)
            self.aux2 = _AuxHead(528, num_classes)

    def forward(self, x):
        x = self.stem(x)
        x = self.pool3(self.ince3b(self.ince3a(x)))
        x = self.ince4a(x)
        aux1 = self.aux1(x) if (self.num_classes > 0 and self.training) \
            else None
        x = self.ince4d(self.ince4c(self.ince4b(x)))
        aux2 = self.aux2(x) if (self.num_classes > 0 and self.training) \
            else None
        x = self.pool4(self.ince4e(x))
        x = self.ince5b(self.ince5a(x))
        if self.with_pool:
            x = self.pool5(x)
        if self.num_classes > 0:
            x = self.fc(self.drop(flatten(x, 1)))
        if self.training and self.num_classes > 0:
            return x, aux1, aux2
        return x


def googlenet(pretrained=False, **kwargs):
    return GoogLeNet(**kwargs)
