from .lenet import LeNet  # noqa: F401
from .resnet import (  # noqa: F401
    ResNet, BasicBlock, BottleneckBlock, resnet18, resnet34, resnet50,
    resnet101, resnet152, wide_resnet50_2, wide_resnet101_2,
    resnext50_32x4d, resnext101_32x4d,
)
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19  # noqa: F401
from .mobilenet import (  # noqa: F401
    MobileNetV1, MobileNetV2, mobilenet_v1, mobilenet_v2,
)
from .densenet import (  # noqa: F401
    DenseNet, densenet121, densenet161, densenet169, densenet201,
    densenet264,
)
from .shufflenetv2 import (  # noqa: F401
    ShuffleNetV2, shufflenet_v2_x0_25, shufflenet_v2_x0_33,
    shufflenet_v2_x0_5, shufflenet_v2_x1_0, shufflenet_v2_x1_5,
    shufflenet_v2_x2_0,
)
from .googlenet import GoogLeNet, googlenet  # noqa: F401
from .inceptionv3 import InceptionV3, inception_v3  # noqa: F401
from .mobilenetv3 import (  # noqa: F401
    MobileNetV3Large, MobileNetV3Small, mobilenet_v3_large,
    mobilenet_v3_small,
)
from .alexnet import (  # noqa: F401
    AlexNet, SqueezeNet, alexnet, squeezenet1_1,
)
