"""AlexNet / SqueezeNet — parity: `python/paddle/vision/models/alexnet.py`,
`squeezenet.py`."""
from __future__ import annotations

from ... import nn
from ...ops.manipulation import flatten, concat


class AlexNet(nn.Layer):
    def __init__(self, num_classes=1000):
        super().__init__()
        self.num_classes = num_classes
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 11, stride=4, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(64, 192, 5, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(192, 384, 3, padding=1), nn.ReLU(),
            nn.Conv2D(384, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2D(256, 256, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, 2),
        )
        self.avgpool = nn.AdaptiveAvgPool2D((6, 6))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(), nn.Linear(256 * 36, 4096), nn.ReLU(),
                nn.Dropout(), nn.Linear(4096, 4096), nn.ReLU(),
                nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.avgpool(self.features(x))
        if self.num_classes > 0:
            x = self.classifier(flatten(x, 1))
        return x


class _Fire(nn.Layer):
    def __init__(self, inp, squeeze, e1, e3):
        super().__init__()
        self.squeeze = nn.Conv2D(inp, squeeze, 1)
        self.relu = nn.ReLU()
        self.expand1 = nn.Conv2D(squeeze, e1, 1)
        self.expand3 = nn.Conv2D(squeeze, e3, 3, padding=1)

    def forward(self, x):
        s = self.relu(self.squeeze(x))
        return concat([self.relu(self.expand1(s)),
                       self.relu(self.expand3(s))], axis=1)


class SqueezeNet(nn.Layer):
    def __init__(self, version="1.1", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
            nn.MaxPool2D(3, 2),
            _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
            nn.MaxPool2D(3, 2),
            _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
            _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256),
        )
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.5),
                nn.Conv2D(512, num_classes, 1), nn.ReLU(),
                nn.AdaptiveAvgPool2D((1, 1)))

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.classifier(x)
            x = flatten(x, 1)
        return x


def alexnet(pretrained=False, **kwargs):
    return AlexNet(**kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    return SqueezeNet("1.1", **kwargs)
