"""MobileNetV1/V2 — parity: `python/paddle/vision/models/mobilenetv1.py`,
`mobilenetv2.py` (depthwise-separable convs)."""
from __future__ import annotations

from ... import nn


def _conv_bn(inp, oup, stride, kernel=3, groups=1):
    return nn.Sequential(
        nn.Conv2D(inp, oup, kernel, stride,
                  padding=(kernel - 1) // 2, groups=groups,
                  bias_attr=False),
        nn.BatchNorm2D(oup),
        nn.ReLU())


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return max(8, int(ch * scale))
        cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
               (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 + \
            [(512, 1024, 2), (1024, 1024, 1)]
        layers = [_conv_bn(3, c(32), 2)]
        for inp, oup, s in cfg:
            layers.append(_conv_bn(c(inp), c(inp), s, groups=c(inp)))
            layers.append(_conv_bn(c(inp), c(oup), 1, kernel=1))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(c(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ...ops.manipulation import flatten
            x = self.fc(flatten(x, 1))
        return x


class InvertedResidual(nn.Layer):
    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        hidden = int(round(inp * expand_ratio))
        self.use_res = stride == 1 and inp == oup
        layers = []
        if expand_ratio != 1:
            layers.append(_conv_bn(inp, hidden, 1, kernel=1))
        layers += [
            _conv_bn(hidden, hidden, stride, groups=hidden),
            nn.Conv2D(hidden, oup, 1, bias_attr=False),
            nn.BatchNorm2D(oup),
        ]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2),
               (6, 64, 4, 2), (6, 96, 3, 1), (6, 160, 3, 2),
               (6, 320, 1, 1)]

        def c(ch):
            return max(8, int(ch * scale))
        layers = [_conv_bn(3, c(32), 2)]
        inp = c(32)
        for t, ch, n, s in cfg:
            for i in range(n):
                layers.append(InvertedResidual(
                    inp, c(ch), s if i == 0 else 1, t))
                inp = c(ch)
        layers.append(_conv_bn(inp, c(1280), 1, kernel=1))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.2), nn.Linear(c(1280), num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ...ops.manipulation import flatten
            x = self.classifier(flatten(x, 1))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV2(scale=scale, **kwargs)
