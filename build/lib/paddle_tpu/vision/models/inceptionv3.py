"""InceptionV3 — parity: `python/paddle/vision/models/inceptionv3.py`
(299x299 stem, factorized 7x7 branches, grid-reduction blocks)."""
from __future__ import annotations

from ... import nn
from ...ops.manipulation import concat, flatten


def _cbr(inp, oup, k, stride=1, padding=0):
    if isinstance(k, int):
        k = (k, k)
    if isinstance(padding, int):
        padding = (padding, padding)
    return nn.Sequential(
        nn.Conv2D(inp, oup, k, stride=stride, padding=padding,
                  bias_attr=False),
        nn.BatchNorm2D(oup), nn.ReLU())


class _InceptionA(nn.Layer):
    def __init__(self, inp, pool_feat):
        super().__init__()
        self.b1 = _cbr(inp, 64, 1)
        self.b5 = nn.Sequential(_cbr(inp, 48, 1),
                                _cbr(48, 64, 5, padding=2))
        self.b3 = nn.Sequential(_cbr(inp, 64, 1),
                                _cbr(64, 96, 3, padding=1),
                                _cbr(96, 96, 3, padding=1))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _cbr(inp, pool_feat, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b5(x), self.b3(x), self.bp(x)],
                      axis=1)


class _ReductionA(nn.Layer):
    def __init__(self, inp):
        super().__init__()
        self.b3 = _cbr(inp, 384, 3, stride=2)
        self.b3d = nn.Sequential(_cbr(inp, 64, 1),
                                 _cbr(64, 96, 3, padding=1),
                                 _cbr(96, 96, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return concat([self.b3(x), self.b3d(x), self.pool(x)], axis=1)


class _InceptionB(nn.Layer):
    """Factorized 7x7 branches."""

    def __init__(self, inp, ch7):
        super().__init__()
        self.b1 = _cbr(inp, 192, 1)
        self.b7 = nn.Sequential(
            _cbr(inp, ch7, 1),
            _cbr(ch7, ch7, (1, 7), padding=(0, 3)),
            _cbr(ch7, 192, (7, 1), padding=(3, 0)))
        self.b7d = nn.Sequential(
            _cbr(inp, ch7, 1),
            _cbr(ch7, ch7, (7, 1), padding=(3, 0)),
            _cbr(ch7, ch7, (1, 7), padding=(0, 3)),
            _cbr(ch7, ch7, (7, 1), padding=(3, 0)),
            _cbr(ch7, 192, (1, 7), padding=(0, 3)))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _cbr(inp, 192, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b7(x), self.b7d(x), self.bp(x)],
                      axis=1)


class _ReductionB(nn.Layer):
    def __init__(self, inp):
        super().__init__()
        self.b3 = nn.Sequential(_cbr(inp, 192, 1),
                                _cbr(192, 320, 3, stride=2))
        self.b7 = nn.Sequential(
            _cbr(inp, 192, 1),
            _cbr(192, 192, (1, 7), padding=(0, 3)),
            _cbr(192, 192, (7, 1), padding=(3, 0)),
            _cbr(192, 192, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return concat([self.b3(x), self.b7(x), self.pool(x)], axis=1)


class _InceptionC(nn.Layer):
    def __init__(self, inp):
        super().__init__()
        self.b1 = _cbr(inp, 320, 1)
        self.b3_stem = _cbr(inp, 384, 1)
        self.b3_a = _cbr(384, 384, (1, 3), padding=(0, 1))
        self.b3_b = _cbr(384, 384, (3, 1), padding=(1, 0))
        self.b3d_stem = nn.Sequential(_cbr(inp, 448, 1),
                                      _cbr(448, 384, 3, padding=1))
        self.b3d_a = _cbr(384, 384, (1, 3), padding=(0, 1))
        self.b3d_b = _cbr(384, 384, (3, 1), padding=(1, 0))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _cbr(inp, 192, 1))

    def forward(self, x):
        s = self.b3_stem(x)
        d = self.b3d_stem(x)
        return concat([self.b1(x),
                       self.b3_a(s), self.b3_b(s),
                       self.b3d_a(d), self.b3d_b(d),
                       self.bp(x)], axis=1)


class InceptionV3(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.stem = nn.Sequential(
            _cbr(3, 32, 3, stride=2), _cbr(32, 32, 3),
            _cbr(32, 64, 3, padding=1), nn.MaxPool2D(3, stride=2),
            _cbr(64, 80, 1), _cbr(80, 192, 3),
            nn.MaxPool2D(3, stride=2))
        self.blocks = nn.Sequential(
            _InceptionA(192, 32), _InceptionA(256, 64),
            _InceptionA(288, 64), _ReductionA(288),
            _InceptionB(768, 128), _InceptionB(768, 160),
            _InceptionB(768, 160), _InceptionB(768, 192),
            _ReductionB(768),
            _InceptionC(1280), _InceptionC(2048))
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.drop = nn.Dropout(0.5)
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.drop(flatten(x, 1)))
        return x


def inception_v3(pretrained=False, **kwargs):
    return InceptionV3(**kwargs)
