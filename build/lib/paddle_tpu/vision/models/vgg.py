"""VGG — parity: `python/paddle/vision/models/vgg.py`."""
from __future__ import annotations

from ... import nn

_CFGS = {
    "A": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "B": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512,
          512, "M"],
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512,
          "M", 512, 512, 512, "M"],
    "E": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512,
          512, 512, "M", 512, 512, 512, 512, "M"],
}


def _make_features(cfg, batch_norm=False):
    layers = []
    in_c = 3
    for v in cfg:
        if v == "M":
            layers.append(nn.MaxPool2D(kernel_size=2, stride=2))
        else:
            layers.append(nn.Conv2D(in_c, v, kernel_size=3, padding=1))
            if batch_norm:
                layers.append(nn.BatchNorm2D(v))
            layers.append(nn.ReLU())
            in_c = v
    return nn.Sequential(*layers)


class VGG(nn.Layer):
    def __init__(self, features, num_classes=1000, with_pool=True):
        super().__init__()
        self.features = features
        self.num_classes = num_classes
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((7, 7))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(512 * 7 * 7, 4096), nn.ReLU(), nn.Dropout(),
                nn.Linear(4096, 4096), nn.ReLU(), nn.Dropout(),
                nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            from ...ops.manipulation import flatten
            x = flatten(x, 1)
            x = self.classifier(x)
        return x


def vgg11(pretrained=False, batch_norm=False, **kwargs):
    return VGG(_make_features(_CFGS["A"], batch_norm), **kwargs)


def vgg13(pretrained=False, batch_norm=False, **kwargs):
    return VGG(_make_features(_CFGS["B"], batch_norm), **kwargs)


def vgg16(pretrained=False, batch_norm=False, **kwargs):
    return VGG(_make_features(_CFGS["D"], batch_norm), **kwargs)


def vgg19(pretrained=False, batch_norm=False, **kwargs):
    return VGG(_make_features(_CFGS["E"], batch_norm), **kwargs)
