"""DenseNet — parity: `python/paddle/vision/models/densenet.py`
(densenet121/161/169/201/264). Dense connectivity: each layer's input is
the channel-concat of all previous layers' outputs in the block; BN-ReLU-
Conv pre-activation ordering."""
from __future__ import annotations

from ... import nn
from ...ops.manipulation import concat, flatten


class _DenseLayer(nn.Layer):
    def __init__(self, in_ch, growth_rate, bn_size, dropout):
        super().__init__()
        inter = bn_size * growth_rate
        self.norm1 = nn.BatchNorm2D(in_ch)
        self.relu = nn.ReLU()
        self.conv1 = nn.Conv2D(in_ch, inter, 1, bias_attr=False)
        self.norm2 = nn.BatchNorm2D(inter)
        self.conv2 = nn.Conv2D(inter, growth_rate, 3, padding=1,
                               bias_attr=False)
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x):
        y = self.conv1(self.relu(self.norm1(x)))
        y = self.conv2(self.relu(self.norm2(y)))
        if self.dropout is not None:
            y = self.dropout(y)
        return concat([x, y], axis=1)


class _DenseBlock(nn.Layer):
    def __init__(self, n_layers, in_ch, growth_rate, bn_size, dropout):
        super().__init__()
        self.layers = nn.LayerList([
            _DenseLayer(in_ch + i * growth_rate, growth_rate, bn_size,
                        dropout) for i in range(n_layers)])
        self.out_channels = in_ch + n_layers * growth_rate

    def forward(self, x):
        for layer in self.layers:
            x = layer(x)
        return x


class _Transition(nn.Layer):
    def __init__(self, in_ch, out_ch):
        super().__init__()
        self.norm = nn.BatchNorm2D(in_ch)
        self.relu = nn.ReLU()
        self.conv = nn.Conv2D(in_ch, out_ch, 1, bias_attr=False)
        self.pool = nn.AvgPool2D(2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.norm(x))))


_CFG = {
    121: (64, 32, (6, 12, 24, 16)),
    161: (96, 48, (6, 12, 36, 24)),
    169: (64, 32, (6, 12, 32, 32)),
    201: (64, 32, (6, 12, 48, 32)),
    264: (64, 32, (6, 12, 64, 48)),
}


class DenseNet(nn.Layer):
    def __init__(self, layers=121, bn_size=4, dropout=0.0,
                 num_classes=1000, with_pool=True):
        super().__init__()
        init_ch, growth, block_cfg = _CFG[layers]
        self.stem = nn.Sequential(
            nn.Conv2D(3, init_ch, 7, stride=2, padding=3,
                      bias_attr=False),
            nn.BatchNorm2D(init_ch), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1))
        blocks = []
        ch = init_ch
        for i, n in enumerate(block_cfg):
            blk = _DenseBlock(n, ch, growth, bn_size, dropout)
            blocks.append(blk)
            ch = blk.out_channels
            if i != len(block_cfg) - 1:
                blocks.append(_Transition(ch, ch // 2))
                ch = ch // 2
        self.blocks = nn.Sequential(*blocks)
        self.norm5 = nn.BatchNorm2D(ch)
        self.relu5 = nn.ReLU()
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Linear(ch, num_classes)

    def forward(self, x):
        x = self.relu5(self.norm5(self.blocks(self.stem(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(flatten(x, 1))
        return x


def densenet121(pretrained=False, **kwargs):
    return DenseNet(layers=121, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return DenseNet(layers=161, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return DenseNet(layers=169, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return DenseNet(layers=201, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return DenseNet(layers=264, **kwargs)
