from . import models  # noqa
