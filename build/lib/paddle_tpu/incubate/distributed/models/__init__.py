from . import moe  # noqa
