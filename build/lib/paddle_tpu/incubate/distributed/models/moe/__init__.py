"""MoE user API.

Parity: `python/paddle/incubate/distributed/models/moe/` (`MoELayer`
(moe_layer.py), gates: NaiveGate/GShardGate/SwitchGate, comm via
global_scatter/global_gather ops `collective/global_scatter_op.cu.cc`).

TPU-native: the dispatch/combine is the dense one-hot + `lax.all_to_all`
implementation in parallel/hybrid_gpt._moe_ffn; this module provides the
layer/gate class surface over it. Inside a compiled sharded step with an
"ep" (=dp) mesh axis the all_to_all rides ICI; on one chip it degrades to
a dense grouped-FFN.
"""
from .gate import NaiveGate, GShardGate, SwitchGate, BaseGate  # noqa
from .moe_layer import MoELayer  # noqa
