"""MoE gates — parity: `python/paddle/incubate/distributed/models/moe/gate/`
(naive_gate.py, gshard_gate.py, switch_gate.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .....nn.layer_base import Layer
from .....nn.layers.common import Linear
from .....core.tensor import Tensor
from .....core import dispatch
from .....ops._helpers import as_tensor


class BaseGate(Layer):
    def __init__(self, d_model, num_expert, world_size=1, topk=2):
        super().__init__()
        self.d_model = d_model
        self.num_expert = num_expert
        self.world_size = world_size
        self.tot_expert = num_expert * world_size
        self.topk = topk
        self.loss = None

    def get_loss(self):
        return self.loss


class NaiveGate(BaseGate):
    """Top-k softmax gate, no auxiliary loss."""

    def __init__(self, d_model, num_expert, world_size=1, topk=2):
        super().__init__(d_model, num_expert, world_size, topk)
        self.gate = Linear(d_model, self.tot_expert)

    def forward(self, inp):
        logits = self.gate(inp)
        k = self.topk

        def _fn(lg):
            val, idx = jax.lax.top_k(lg, k)
            return jax.nn.softmax(val, axis=-1), idx
        val, idx = dispatch.apply("naive_gate", _fn, (as_tensor(logits),))
        return val, idx


class SwitchGate(BaseGate):
    """Top-1 switch gate with load-balance aux loss."""

    def __init__(self, d_model, num_expert, world_size=1, topk=1,
                 switch_eps=0.1, capacity=(1.2, 2.4)):
        super().__init__(d_model, num_expert, world_size, 1)
        self.gate = Linear(d_model, self.tot_expert)
        self.switch_eps = switch_eps
        self.capacity = capacity

    def forward(self, inp):
        logits = self.gate(inp)
        E = self.tot_expert
        cap_factor = self.capacity[0] if self.training else self.capacity[1]

        def _fn(lg):
            T = lg.shape[0]
            cap = max(1, int(cap_factor * T / E))
            probs = jax.nn.softmax(lg.astype(jnp.float32), axis=-1)
            idx = jnp.argmax(probs, axis=-1)
            val = jnp.max(probs, axis=-1)
            oh = jax.nn.one_hot(idx, E, dtype=jnp.int32)
            me = jnp.mean(probs, axis=0)
            ce = jnp.mean(oh.astype(jnp.float32), axis=0)
            aux = E * jnp.sum(me * ce)
            # capacity: zero the gate of overflow tokens (reference
            # prune_gate_by_capacity op)
            pos = jnp.sum(jnp.cumsum(oh, axis=0) * oh - oh, axis=-1)
            val = jnp.where(pos < cap, val, 0.0)
            return val[:, None], idx[:, None].astype(jnp.int32), aux
        val, idx, aux = dispatch.apply("switch_gate", _fn,
                                       (as_tensor(logits),))
        self.loss = aux
        return val, idx


class GShardGate(BaseGate):
    """Top-2 gate with GShard aux loss."""

    def __init__(self, d_model, num_expert, world_size=1, topk=2,
                 capacity=(1.2, 2.4), random_routing=True):
        super().__init__(d_model, num_expert, world_size, 2)
        self.gate = Linear(d_model, self.tot_expert)
        self.capacity = capacity
        self.random_routing = random_routing

    def forward(self, inp):
        logits = self.gate(inp)
        E = self.tot_expert
        cap_factor = self.capacity[0] if self.training else self.capacity[1]
        do_random = self.random_routing and self.training
        from .....core import random as rng
        rkey = rng.next_key() if do_random else None

        def _fn(lg):
            T = lg.shape[0]
            cap = max(1, int(cap_factor * T / E))
            probs = jax.nn.softmax(lg.astype(jnp.float32), axis=-1)
            val, idx = jax.lax.top_k(probs, 2)
            top1 = idx[:, 0]
            oh1 = jax.nn.one_hot(top1, E, dtype=jnp.int32)
            me = jnp.mean(probs, axis=0)
            ce = jnp.mean(oh1.astype(jnp.float32), axis=0)
            aux = E * jnp.sum(me * ce)
            # capacity-prune the primary expert (secondary experts keep
            # their gate — GShard prunes them after dispatch)
            pos = jnp.sum(jnp.cumsum(oh1, axis=0) * oh1 - oh1, axis=-1)
            val = val.at[:, 0].set(jnp.where(pos < cap, val[:, 0], 0.0))
            if do_random:
                # GShard random routing: keep the 2nd expert with
                # probability proportional to its gate (2*g2), else drop
                u = jax.random.uniform(rkey, (T,))
                keep2 = u < 2.0 * val[:, 1]
                val = val.at[:, 1].set(jnp.where(keep2, val[:, 1], 0.0))
            return val / jnp.maximum(
                jnp.sum(val, -1, keepdims=True), 1e-12), \
                idx.astype(jnp.int32), aux
        val, idx, aux = dispatch.apply("gshard_gate", _fn,
                                       (as_tensor(logits),))
        self.loss = aux
        return val, idx
