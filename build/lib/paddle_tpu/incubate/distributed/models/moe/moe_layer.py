"""MoELayer — parity: moe_layer.py `MoELayer(gate, experts, ...)`.

Top-k dispatch/combine implemented densely (one-hot einsum, TPU-friendly);
the expert-parallel all_to_all happens when the surrounding step is
compiled over a mesh with the experts sharded (hybrid_gpt's _moe_ffn path);
eager single-controller execution evaluates experts locally.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .....nn.layer_base import Layer
from .....nn.container import LayerList
from .....core.tensor import Tensor
from .....core import dispatch
from .....ops._helpers import as_tensor
from .gate import NaiveGate, SwitchGate, GShardGate


class MoELayer(Layer):
    """moe_layer.py:MoELayer parity: inp [B, S, d] -> [B, S, d]."""

    def __init__(self, d_model, experts=None, gate=None, moe_group=None,
                 mp_group=None, recompute_interval=0, **kwargs):
        super().__init__()
        self.d_model = d_model
        if isinstance(gate, dict):
            gtype = gate.get("type", "gshard")
            topk = gate.get("top_k", 2)
            n_exp = len(experts)
            cls = {"naive": NaiveGate, "switch": SwitchGate,
                   "gshard": GShardGate}[gtype]
            gate = cls(d_model, n_exp, topk=topk)
        self.gate = gate
        self.experts = experts if isinstance(experts, LayerList) \
            else LayerList(experts)
        self.num_expert = len(self.experts)

    def forward(self, inp):
        inp = as_tensor(inp)
        shape = inp.shape
        d = shape[-1]
        from ..... import ops
        x = ops.reshape(inp, [-1, d])  # [T, d]
        gate_val, gate_idx = self.gate(x)  # [T, k], [T, k]
        E = self.num_expert

        # run every expert on all tokens, combine by gates (dense combine;
        # the sparse dispatch version lives in the compiled hybrid path)
        expert_outs = [ops.unsqueeze(exp(x), 1) for exp in self.experts]
        stacked = ops.concat(expert_outs, axis=1)  # [T, E, d]

        gv, gi, st = as_tensor(gate_val), as_tensor(gate_idx), \
            as_tensor(stacked)

        def _fn(val, idx, outs):
            mask = jax.nn.one_hot(idx, E, dtype=outs.dtype)  # [T,k,E]
            w = jnp.einsum("tk,tke->te", val.astype(outs.dtype), mask)
            return jnp.einsum("te,ted->td", w, outs)
        out = dispatch.apply("moe_combine", _fn, (gv, gi, st))
        return ops.reshape(out, shape)
