"""Fused layers — the LLM-serving stack.

Parity: `python/paddle/incubate/nn/layer/fused_transformer.py`. Real
TPU-native implementations live in `fused_transformer.py` (stacked
weights + `lax.scan`, fixed-shape KV cache, weight-only int8, MoE) and
`generation.py` (compiled greedy/sampling decode).
"""
from __future__ import annotations

from .fused_transformer import (  # noqa: F401
    FusedBiasDropoutResidualLayerNorm,
    FusedMultiHeadAttention,
    FusedFeedForward,
    FusedTransformerEncoderLayer,
    FusedMultiTransformer,
    FusedMultiTransformerWeightOnly,
    FusedMultiTransformerINT8,
    FusedMultiTransformerMoe,
    FusedMultiTransformerMoeWeightOnly,
    FusedMultiTransformerMoeINT8,
    FusedMoELayer,
)
from .generation import GenerationMixin, SamplingConfig  # noqa: F401
