"""paddle_tpu.incubate — incubating APIs (`python/paddle/incubate/`).
MoE lives in paddle_tpu.incubate.distributed.models.moe (parity path).
"""
from . import nn  # noqa: F401
from . import autotune  # noqa: F401
from . import distributed  # noqa: F401
from . import autograd  # noqa: F401
from . import asp  # noqa: F401
from . import optimizer  # noqa: F401
