"""Layout autotune — NCHW models rewritten to channels-last.

Parity: the reference's layout autotune
(`paddle/fluid/imperative/layout_autotune.cc`, enabled via
`paddle.incubate.autotune.set_config(... "layout": ...)`) rewrites a
dygraph NCHW program to NHWC for tensor-core GPUs, inserting boundary
transposes.

On TPU the stakes are higher: vector registers tile the two MINOR axes
(8, 128), so NCHW feature maps put W on the 128-lane axis — deep-layer
maps like [B, 512, 7, 7] pad 7 -> 128 (18x memory/bandwidth blowup) and
every elementwise/BN op between convs pays it. Channels-last puts C
(64/128/256/512 in ResNets — tile-aligned) on the lanes: pad-free.

`to_channels_last(model)` flips every layout-aware layer (Conv2D,
BatchNorm2D, SyncBatchNorm, pooling, AdaptiveAvgPool2D) to NHWC in
place and returns the model. The caller feeds NHWC inputs (transpose
once at the input edge: `x.transpose([0, 2, 3, 1])`).

Safe for conv-BN-act-residual topologies (elementwise ops are
layout-agnostic; flatten after a global pool sees [B, 1, 1, C] ==
[B, C] either way). NOT safe for models that index/concat/reshape axis
1 as channels mid-network — those need manual data_format plumbing.
"""
from __future__ import annotations

from ..nn.layer_base import Layer


_FLIP = {"NCHW": "NHWC", "NCL": "NLC", "NCDHW": "NDHWC"}


def to_channels_last(model: Layer) -> Layer:
    """Flip every layout-aware sublayer of `model` to channels-last (in
    place). Feed the model channels-last inputs afterwards."""
    for layer in model.sublayers(include_self=True):
        fmt = getattr(layer, "_data_format", None)
        if fmt in _FLIP:
            layer._data_format = _FLIP[fmt]
        elif fmt is None and layer.__class__.__name__.startswith(
                ("MaxPool", "AvgPool")):
            # pooling layers default to NCHW via `_data_format=None`
            layer._data_format = "NHWC"
        # LocalResponseNorm stores `data_format` without underscore
        fmt2 = getattr(layer, "data_format", None)
        if isinstance(fmt2, str) and fmt2 in _FLIP:
            layer.data_format = _FLIP[fmt2]
    return model


def set_config(config=None):
    """`paddle.incubate.autotune.set_config` shim: accepts the reference
    config dict; layout autotune maps to `to_channels_last` (explicit —
    the implicit per-op rewrite doesn't exist here because XLA already
    owns kernel selection/fusion)."""
    layout_cfg = config.get("layout") if isinstance(config, dict) else None
    if isinstance(layout_cfg, dict) and layout_cfg.get("enable", False):
        import warnings
        warnings.warn(
            "layout autotune via set_config is a no-op here: XLA owns "
            "kernel selection, and the implicit per-op NCHW->NHWC rewrite "
            "does not exist. Call "
            "paddle.incubate.autotune.to_channels_last(model) explicitly "
            "and feed channels-last inputs.", stacklevel=2)
    return None
