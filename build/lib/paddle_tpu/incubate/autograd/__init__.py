"""paddle.incubate.autograd — primitive-transform AD.

Parity: `python/paddle/incubate/autograd/` (primops/primx forward+reverse
prim transforms). TPU-native: jax's functional transforms ARE the
primitive AD system; these wrappers expose jvp/vjp/jacobian/hessian over
Tensor-valued functions.
"""
from __future__ import annotations

import jax

from ...core.tensor import Tensor
from ...core import autograd as _ag


def _wrap_fn(func):
    def pure(*arrays):
        tensors = [Tensor(a) for a in arrays]
        with _ag.no_grad():
            out = func(*tensors)
        if isinstance(out, (list, tuple)):
            return tuple(o._data for o in out)
        return out._data
    return pure


def _unwrap(xs):
    return tuple(x._data if isinstance(x, Tensor) else x for x in xs)


def jvp(func, primals, tangents):
    primals = primals if isinstance(primals, (list, tuple)) else [primals]
    tangents = tangents if isinstance(tangents, (list, tuple)) \
        else [tangents]
    out, tan = jax.jvp(_wrap_fn(func), _unwrap(primals), _unwrap(tangents))
    wrap = lambda o: tuple(Tensor(v) for v in o) \
        if isinstance(o, tuple) else Tensor(o)  # noqa: E731
    return wrap(out), wrap(tan)


def vjp(func, primals, cotangents=None):
    primals = primals if isinstance(primals, (list, tuple)) else [primals]
    out, vjp_fn = jax.vjp(_wrap_fn(func), *_unwrap(primals))
    if cotangents is None:
        import jax.numpy as jnp
        cotangents = jax.tree.map(jnp.ones_like, out)
    else:
        cts = cotangents if isinstance(cotangents, (list, tuple)) \
            else [cotangents]
        cotangents = tuple(c._data if isinstance(c, Tensor) else c
                           for c in cts)
        if not isinstance(out, tuple):
            cotangents = cotangents[0]
    grads = vjp_fn(cotangents)
    wrap = lambda o: tuple(Tensor(v) for v in o) \
        if isinstance(o, tuple) else Tensor(o)  # noqa: E731
    return wrap(out), [Tensor(g) for g in grads]


def Jacobian(func, xs, is_batched=False):
    xs_l = xs if isinstance(xs, (list, tuple)) else [xs]
    jac = jax.jacobian(_wrap_fn(func), argnums=tuple(range(len(xs_l))))(
        *_unwrap(xs_l))
    return jax.tree.map(Tensor, jac)


def Hessian(func, xs, is_batched=False):
    xs_l = xs if isinstance(xs, (list, tuple)) else [xs]
    hes = jax.hessian(_wrap_fn(func), argnums=tuple(range(len(xs_l))))(
        *_unwrap(xs_l))
    return jax.tree.map(Tensor, hes)
