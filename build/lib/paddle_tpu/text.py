"""paddle_tpu.text — `python/paddle/text/` parity essentials.

Datasets are zero-egress synthetic stand-ins (same API shapes); the real
op here is viterbi_decode (`paddle.text.viterbi_decode`,
`paddle/phi/kernels/viterbi_decode_kernel.h`) as a lax.scan.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .core import dispatch
from .core.tensor import Tensor
from .ops._helpers import as_tensor
from .io import Dataset


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """potentials [B, T, N], transition [N, N] (+2 rows/cols when
    include_bos_eos_tag, matching the reference layout where the last two
    tags are BOS/EOS). `lengths` [B] masks padded timesteps (required
    input in the reference; defaults to full length here).
    Returns (scores [B], paths [B, T])."""
    potentials = as_tensor(potentials)
    transition_params = as_tensor(transition_params)
    B, T, N = potentials.shape
    if lengths is None:
        lengths = np.full((B,), T, np.int32)
    lengths = as_tensor(lengths)

    def _fn(pot, trans, lens):
        if include_bos_eos_tag:
            start = trans[-2][:N]
            stop = trans[:N, -1]
            trans_core = trans[:N, :N]
        else:
            start = jnp.zeros((N,))
            stop = jnp.zeros((N,))
            trans_core = trans

        alpha0 = pot[:, 0] + start[None, :]
        ident = jnp.broadcast_to(jnp.arange(N)[None, :], (B, N))

        def step(alpha, xs):
            emit, t = xs
            valid = (t < lens)[:, None]            # [B,1]
            scores = alpha[:, :, None] + trans_core[None]
            best = jnp.max(scores, axis=1) + emit
            back = jnp.argmax(scores, axis=1)
            # frozen past each sequence's end: alpha carries, backpointer
            # is identity so backtracking repeats the final tag
            alpha_new = jnp.where(valid, best, alpha)
            back = jnp.where(valid, back, ident)
            return alpha_new, back

        ts = jnp.arange(1, T)
        alpha_f, backs = jax.lax.scan(
            step, alpha0, (jnp.swapaxes(pot[:, 1:], 0, 1), ts))
        alpha_f = alpha_f + stop[None, :]
        scores = jnp.max(alpha_f, axis=-1)
        last = jnp.argmax(alpha_f, axis=-1)

        def backtrack(carry, back):
            tag = carry
            prev = jnp.take_along_axis(back, tag[:, None], axis=1)[:, 0]
            return prev, prev

        _, path_rev = jax.lax.scan(backtrack, last, backs, reverse=True)
        paths = jnp.concatenate([jnp.swapaxes(path_rev, 0, 1),
                                 last[:, None]], axis=1)
        return scores, paths.astype(jnp.int32)
    return dispatch.apply("viterbi_decode", _fn,
                          (potentials, transition_params, lengths))


class _SyntheticTextDataset(Dataset):
    def __init__(self, size, seq_len, vocab, n_classes, seed):
        rng = np.random.RandomState(seed)
        self.x = rng.randint(1, vocab, (size, seq_len)).astype(np.int64)
        self.y = rng.randint(0, n_classes, (size,)).astype(np.int64)

    def __getitem__(self, idx):
        return self.x[idx], np.array([self.y[idx]], np.int64)

    def __len__(self):
        return len(self.x)


class Imdb(_SyntheticTextDataset):
    """API-shaped stand-in (zero-egress image)."""

    def __init__(self, mode="train", cutoff=150):
        super().__init__(2000 if mode == "train" else 400, 64, 5000, 2,
                         0 if mode == "train" else 1)


class UCIHousing(Dataset):
    def __init__(self, mode="train"):
        rng = np.random.RandomState(2 if mode == "train" else 3)
        n = 404 if mode == "train" else 102
        self.x = rng.randn(n, 13).astype(np.float32)
        w = rng.randn(13).astype(np.float32)
        self.y = (self.x @ w + 0.1 * rng.randn(n)).astype(np.float32)

    def __getitem__(self, idx):
        return self.x[idx], np.array([self.y[idx]], np.float32)

    def __len__(self):
        return len(self.x)
