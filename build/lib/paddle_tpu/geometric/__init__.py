"""paddle_tpu.geometric — graph learning ops.

Parity: `python/paddle/geometric/` (segment_sum/mean/max/min,
send_u_recv message passing) over XLA segment ops — the compute core the
reference's GPU graph engine feeds (`paddle/phi/kernels/
segment_pool_kernel.h`, `graph_send_recv_kernel.h`).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dispatch
from ..core.tensor import Tensor
from ..ops._helpers import as_tensor


def _segment(name, jfn, data, segment_ids):
    data, segment_ids = as_tensor(data), as_tensor(segment_ids)
    n_seg = int(np.asarray(segment_ids.numpy()).max()) + 1 \
        if segment_ids.size else 0

    def _fn(d, s):
        return jfn(d, s, num_segments=n_seg)
    return dispatch.apply(name, _fn, (data, segment_ids))


def segment_sum(data, segment_ids, name=None):
    return _segment("segment_sum", jax.ops.segment_sum, data, segment_ids)


def segment_mean(data, segment_ids, name=None):
    data_t, seg_t = as_tensor(data), as_tensor(segment_ids)
    n_seg = int(np.asarray(seg_t.numpy()).max()) + 1 if seg_t.size else 0

    def _fn(d, s):
        sums = jax.ops.segment_sum(d, s, num_segments=n_seg)
        counts = jax.ops.segment_sum(jnp.ones((d.shape[0],), d.dtype), s,
                                     num_segments=n_seg)
        return sums / jnp.maximum(counts, 1.0).reshape(
            (-1,) + (1,) * (d.ndim - 1))
    return dispatch.apply("segment_mean", _fn, (data_t, seg_t))


def segment_max(data, segment_ids, name=None):
    return _segment("segment_max", jax.ops.segment_max, data, segment_ids)


def segment_min(data, segment_ids, name=None):
    return _segment("segment_min", jax.ops.segment_min, data, segment_ids)


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Message passing: gather x[src] and segment-reduce onto dst
    (graph_send_recv parity)."""
    x, src_index, dst_index = (as_tensor(x), as_tensor(src_index),
                               as_tensor(dst_index))
    n_out = int(out_size) if out_size is not None else \
        int(np.asarray(dst_index.numpy()).max()) + 1
    red = {"sum": jax.ops.segment_sum, "max": jax.ops.segment_max,
           "min": jax.ops.segment_min}.get(reduce_op)

    def _fn(xa, src, dst):
        msgs = jnp.take(xa, src, axis=0)
        if reduce_op == "mean":
            sums = jax.ops.segment_sum(msgs, dst, num_segments=n_out)
            counts = jax.ops.segment_sum(
                jnp.ones((msgs.shape[0],), xa.dtype), dst,
                num_segments=n_out)
            return sums / jnp.maximum(counts, 1.0).reshape(
                (-1,) + (1,) * (xa.ndim - 1))
        return red(msgs, dst, num_segments=n_out)
    return dispatch.apply("send_u_recv", _fn, (x, src_index, dst_index))


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Node+edge message passing (graph_send_ue_recv parity)."""
    x, y = as_tensor(x), as_tensor(y)
    src_index, dst_index = as_tensor(src_index), as_tensor(dst_index)
    n_out = int(out_size) if out_size is not None else \
        int(np.asarray(dst_index.numpy()).max()) + 1

    def _fn(xa, ya, src, dst):
        msgs = jnp.take(xa, src, axis=0)
        if message_op == "add":
            msgs = msgs + ya
        elif message_op == "mul":
            msgs = msgs * ya
        if reduce_op == "sum":
            return jax.ops.segment_sum(msgs, dst, num_segments=n_out)
        if reduce_op == "max":
            return jax.ops.segment_max(msgs, dst, num_segments=n_out)
        if reduce_op == "min":
            return jax.ops.segment_min(msgs, dst, num_segments=n_out)
        sums = jax.ops.segment_sum(msgs, dst, num_segments=n_out)
        counts = jax.ops.segment_sum(
            jnp.ones((msgs.shape[0],), msgs.dtype), dst,
            num_segments=n_out)
        return sums / jnp.maximum(counts, 1.0).reshape(
            (-1,) + (1,) * (msgs.ndim - 1))
    return dispatch.apply("send_ue_recv", _fn,
                          (x, y, src_index, dst_index))
