"""paddle_tpu.quantization — QAT / PTQ.

Parity: `python/paddle/quantization/` (QuantConfig, QAT with FakeQuant
observers, PTQ with abs-max observers; reference kernels
`paddle/phi/kernels/fake_quantize_*`). TPU-native: scales are computed
on-device and fake-quant is an elementwise round-trip XLA fuses into the
producer (works inside compiled steps; observer state is a registered
buffer so the functional trainer tracks its updates). int8 deployment maps
to XLA int8 dots (weight-only int8 matching the reference's
`weight_only_linear` capability).
"""
from __future__ import annotations

import copy

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dispatch
from ..core.tensor import Tensor
from ..nn.layer_base import Layer
from ..nn.layers.common import Linear
from ..ops._helpers import as_tensor


def fake_quant(x, scale, bits=8):
    """Quantize-dequantize with straight-through gradient
    (fake_quantize_abs_max parity). `scale` may be a python float or a
    Tensor (traced scales work inside compiled steps)."""
    x = as_tensor(x)
    qmax = float(2 ** (bits - 1) - 1)
    if isinstance(scale, Tensor):
        def _fn(a, s):
            s = jnp.maximum(s, 1e-9)
            q = jnp.clip(jnp.round(a / s * qmax), -qmax, qmax)
            deq = q * s / qmax
            return a + jax.lax.stop_gradient(deq - a)
        return dispatch.apply("fake_quant", _fn, (x, scale))
    s = float(scale)

    def _fn1(a):
        q = jnp.clip(jnp.round(a / max(s, 1e-9) * qmax), -qmax, qmax)
        deq = q * s / qmax
        return a + jax.lax.stop_gradient(deq - a)
    from ..ops._helpers import unary
    return unary("fake_quant", _fn1, x)


def abs_max_scale(x):
    x = as_tensor(x)
    return float(np.abs(x.numpy()).max())


class QuantedLinear(Layer):
    """Linear with fake-quantized weight + activation (QAT/PTQ).

    The activation scale is a moving-average abs-max kept in a registered
    buffer — entirely on-device, so compiled train steps trace it and the
    buffer update flows through the functional trainer. Observation
    happens while `training` or while `calibrating` (PTQ flow)."""

    def __init__(self, linear: Linear, bits=8, moving_rate=0.9):
        super().__init__()
        self.linear = linear
        self.bits = bits
        self.moving_rate = moving_rate
        self.calibrating = False
        self.register_buffer("act_scale",
                             Tensor(np.zeros((), np.float32)))

    def forward(self, x):
        from .. import ops
        x = as_tensor(x)
        observing = self.training or self.calibrating
        if observing:
            cur = ops.max(ops.abs(x.detach())).astype("float32")
            prev = Tensor(self.act_scale._data)
            r = self.moving_rate

            def _upd(p, c):
                return jnp.where(p == 0.0, c, r * p + (1 - r) * c)
            new_scale = dispatch.apply("scale_update", _upd, (prev, cur))
            self.act_scale._data = new_scale._data
            a_scale = new_scale
        else:
            a_scale = Tensor(self.act_scale._data)
        w = self.linear.weight
        w_scale = ops.max(ops.abs(w.detach())).astype("float32")
        xq = fake_quant(x, a_scale, self.bits)
        wq = fake_quant(w, w_scale, self.bits)
        from ..nn import functional as F
        return F.linear(xq, wq, self.linear.bias)


class QuantConfig:
    """paddle.quantization.QuantConfig parity (the knobs we consume)."""

    def __init__(self, activation=None, weight=None):
        self.bits = 8
        self.moving_rate = 0.9

    def add_layer_config(self, *a, **k):
        pass


def _swap_linears(model, bits, moving_rate):
    for name, layer in list(model.named_sublayers(include_self=True)):
        for child_name, child in list(layer._sub_layers.items()):
            if isinstance(child, Linear):
                layer._sub_layers[child_name] = QuantedLinear(
                    child, bits, moving_rate)
    return model


def _set_calibrating(model, flag):
    for _, layer in model.named_sublayers(include_self=True):
        if isinstance(layer, QuantedLinear):
            layer.calibrating = flag


class QAT:
    """paddle.quantization.QAT parity: quantize(model) swaps Linear ->
    QuantedLinear (copy unless inplace)."""

    def __init__(self, config: QuantConfig = None):
        self.config = config or QuantConfig()

    def quantize(self, model, inplace=True):
        if not inplace:
            model = copy.deepcopy(model)
        return _swap_linears(model, self.config.bits,
                             self.config.moving_rate)

    def convert(self, model, inplace=True):
        return model


class PTQ:
    """paddle.quantization.PTQ parity: quantize() arms calibration-mode
    observers (they run even in eval), feed sample batches, then
    convert() freezes the scales."""

    def __init__(self, config: QuantConfig = None):
        self.config = config or QuantConfig()

    def quantize(self, model, inplace=True):
        model = QAT(self.config).quantize(model, inplace)
        _set_calibrating(model, True)
        return model

    def convert(self, model, inplace=True):
        _set_calibrating(model, False)
        model.eval()
        return model


def weight_quantize(w, algo="abs_max", bits=8):
    """weight_quantize_kernel parity: returns (int8 weights, scales)."""
    w = as_tensor(w)
    arr = w.numpy()
    qmax = 2 ** (bits - 1) - 1
    scale = np.maximum(np.abs(arr).max(axis=0), 1e-9)  # per-out-channel
    q = np.clip(np.round(arr / scale * qmax), -qmax, qmax).astype(np.int8)
    return Tensor(q), Tensor(scale.astype(np.float32))


def weight_only_linear(x, weight_int8, scale, bias=None, bits=8):
    """weight_only_linear_kernel parity: int8 weights dequantized into a
    bf16 matmul (XLA fuses the dequant into the dot)."""
    x, weight_int8, scale = as_tensor(x), as_tensor(weight_int8), \
        as_tensor(scale)
    qmax = float(2 ** (bits - 1) - 1)
    inputs = [x, weight_int8, scale]
    if bias is not None:
        inputs.append(as_tensor(bias))

    def _fn(a, w_q, s, *b):
        w = w_q.astype(a.dtype) * (s.astype(a.dtype) / qmax)
        out = jnp.matmul(a, w)
        if b:
            out = out + b[0].astype(out.dtype)
        return out
    return dispatch.apply("weight_only_linear", _fn, tuple(inputs))
