"""paddle.hub — parity for the local-source paths (`python/paddle/hub.py`).
Zero-egress image: github sources are rejected with a clear error; local
directories with a hubconf.py work fully.
"""
from __future__ import annotations

import importlib.util
import os
import sys


def _load_hubconf(repo_dir):
    path = os.path.join(repo_dir, "hubconf.py")
    if not os.path.exists(path):
        raise FileNotFoundError(f"no hubconf.py in {repo_dir}")
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _check_source(source):
    if source != "local":
        raise RuntimeError(
            "paddle_tpu.hub supports source='local' only in this "
            "environment (no network egress); clone the repo and pass its "
            "path")


def list(repo_dir, source="local", force_reload=False):
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return [n for n in dir(mod) if callable(getattr(mod, n))
            and not n.startswith("_")]


def help(repo_dir, model, source="local", force_reload=False):
    _check_source(source)
    return getattr(_load_hubconf(repo_dir), model).__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    _check_source(source)
    return getattr(_load_hubconf(repo_dir), model)(**kwargs)
