"""ParamAttr — parameter attribute bundle.

Parity: `python/paddle/fluid/param_attr.py` (`ParamAttr`): name, initializer,
learning_rate multiplier, regularizer, trainable, need_clip.
"""
from __future__ import annotations


class ParamAttr:
    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        """Normalise user input: None -> default, False -> no parameter,
        str -> named, initializer -> wrapped."""
        if attr is None:
            return ParamAttr()
        if attr is False:
            return False
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        # assume it's an initializer object
        return ParamAttr(initializer=attr)
