"""nn.Layer — the module base class.

Parity: `python/paddle/fluid/dygraph/layers.py:98` (`Layer`): parameter /
buffer / sublayer registration via `__setattr__`, `create_parameter`,
forward pre/post hooks, `state_dict` / `set_state_dict`, train/eval modes,
`apply`, `to`. Parameters are `core.Parameter` tensors (stop_gradient=False)
living on the TPU as jax Arrays.
"""
from __future__ import annotations

import collections

import numpy as np

from ..core import dtype as dtype_mod
from ..core.tensor import Parameter, Tensor
from .param_attr import ParamAttr
from . import initializer as I


class HookRemoveHelper:
    def __init__(self, hooks, hook_id):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._dtype = dtype_mod.convert_dtype(dtype)
        self._parameters = collections.OrderedDict()
        self._sub_layers = collections.OrderedDict()
        self._buffers = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._hook_id = 0
        self._name = name_scope or self.__class__.__name__.lower()

    # ---------------------------------------------------------- registry
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning "
                                   "parameters")
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning "
                                   "sublayers")
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            layers[name] = value
            self.__dict__.pop(name, None)
        else:
            if params is not None:
                params.pop(name, None)
            if layers is not None:
                layers.pop(name, None)
            if buffers is not None and isinstance(value, Tensor):
                # plain tensors assigned to a layer become buffers only via
                # register_buffer; a raw assignment stays a python attr
                pass
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for d in ("_parameters", "_sub_layers", "_buffers"):
            store = self.__dict__.get(d)
            if store is not None and name in store:
                return store[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for d in ("_parameters", "_sub_layers", "_buffers"):
            store = self.__dict__.get(d)
            if store is not None and name in store:
                del store[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + \
            list(self._sub_layers) + list(self._buffers)

    # -------------------------------------------------------- parameters
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        """`Layer.create_parameter` parity (layers.py:421) — ParamAttr +
        initializer-driven creation."""
        dtype = dtype_mod.convert_dtype(dtype) or self._dtype
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        init = None
        if default_initializer is not None:
            init = default_initializer
        elif attr is not None and attr.initializer is not None:
            init = attr.initializer
        else:
            init = I.Constant(0.0) if is_bias else I.XavierUniform()
        data = init(shape, dtype)
        trainable = attr.trainable if attr is not None else True
        p = Parameter(data, dtype=dtype,
                      name=attr.name if attr is not None else None,
                      trainable=trainable)
        if attr is not None:
            p.optimize_attr["learning_rate"] = attr.learning_rate
            p.regularizer = attr.regularizer
            p.need_clip = attr.need_clip
        return p

    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter expects a Parameter")
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        """layers.py register_buffer parity (e.g. BN running stats)."""
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        else:
            self._non_persistable_buffer_names.discard(name)
        self.__dict__.pop(name, None)
        return tensor

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self._traverse(prefix, include_sublayers):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (name + ("." if name else "") + pname, p)

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self._traverse(prefix, include_sublayers):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (name + ("." if name else "") + bname, b)

    def _traverse(self, prefix="", include_sublayers=True):
        yield prefix, self
        if include_sublayers:
            for lname, sub in self._sub_layers.items():
                if sub is None:
                    continue
                sub_prefix = prefix + ("." if prefix else "") + lname
                yield from sub._traverse(sub_prefix, True)

    def children(self):
        return iter(self._sub_layers.values())

    def named_children(self):
        return iter(self._sub_layers.items())

    def sublayers(self, include_self=False):
        out = []
        for name, layer in self._traverse("", True):
            if layer is self and not include_self:
                continue
            out.append(layer)
        return out

    def named_sublayers(self, prefix="", include_self=False):
        for name, layer in self._traverse(prefix, True):
            if layer is self and not include_self:
                continue
            yield name, layer

    # ------------------------------------------------------------- modes
    def train(self):
        self.training = True
        for sub in self._sub_layers.values():
            if sub is not None:
                sub.train()
        return self

    def eval(self):
        self.training = False
        for sub in self._sub_layers.values():
            if sub is not None:
                sub.eval()
        return self

    def apply(self, fn):
        for sub in self._sub_layers.values():
            if sub is not None:
                sub.apply(fn)
        fn(self)
        return self

    # ------------------------------------------------------------- hooks
    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # ------------------------------------------------------------ state
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None else \
            collections.OrderedDict()
        for name, p in self.named_parameters(
                prefix=structured_name_prefix,
                include_sublayers=include_sublayers):
            dest[name] = p
        for name, layer in self._traverse(structured_name_prefix,
                                          include_sublayers):
            for bname, b in layer._buffers.items():
                if b is None or bname in layer._non_persistable_buffer_names:
                    continue
                dest[name + ("." if name else "") + bname] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k not in own:
                unexpected.append(k)
                continue
            tgt = own[k]
            arr = v.numpy() if isinstance(v, Tensor) else np.asarray(v)
            tgt.set_value(arr.astype(tgt.dtype))
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    load_dict = set_state_dict
    set_dict = set_state_dict

    # -------------------------------------------------------- conversion
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            self._cast_all(dtype_mod.convert_dtype(dtype))
        return self

    def astype(self, dtype):
        self._cast_all(dtype_mod.convert_dtype(dtype))
        return self

    def _cast_all(self, dt, floating_only=True):
        for _, p in self.named_parameters():
            if not floating_only or dtype_mod.is_floating(p.dtype):
                p._data = p._data.astype(dt)
        for _, b in self.named_buffers():
            if isinstance(b, Tensor) and (
                    not floating_only or dtype_mod.is_floating(b.dtype)):
                b._data = b._data.astype(dt)
        self._dtype = dt

    def float(self):
        self._cast_all(dtype_mod.float32)
        return self

    def bfloat16(self):
        self._cast_all(dtype_mod.bfloat16)
        return self

    def half(self):
        self._cast_all(dtype_mod.float16)
        return self

    # ------------------------------------------------------------- call
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            o = hook(self, inputs, outputs)
            if o is not None:
                outputs = o
        return outputs

    def full_name(self):
        return self._name

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).split("\n")
            sub_repr = "\n  ".join(sub_repr)
            lines.append(f"({name}): {sub_repr}")
        main = self.__class__.__name__ + "(" + extra
        if lines:
            main += "\n  " + "\n  ".join(lines) + "\n"
        return main + ")"
