"""Gradient clipping — `python/paddle/fluid/clip.py` parity
(ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm). The actual clipping
happens inside the optimizer's fused jitted step (optimizer/optimizer.py);
these classes can also be called standalone on (param, grad) lists.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._data, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            n = jnp.sqrt(jnp.sum(jnp.square(g._data.astype(jnp.float32))))
            scale = jnp.minimum(1.0, self.clip_norm / (n + 1e-6))
            out.append((p, Tensor(g._data * scale.astype(g._data.dtype))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def __call__(self, params_grads):
        grads = [g._data for _, g in params_grads if g is not None]
        if not grads:
            return params_grads
        gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                             for g in grads))
        scale = jnp.minimum(1.0, self.clip_norm / (gnorm + 1e-6))
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
            else:
                out.append((p, Tensor(g._data * scale.astype(g._data.dtype))))
        return out
