def _ntuple(v, n):
    if isinstance(v, int):
        return tuple([v] * n)
    return tuple(int(x) for x in v)
