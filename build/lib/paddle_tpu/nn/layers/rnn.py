"""Recurrent layers.

Parity: `python/paddle/nn/layer/rnn.py` (SimpleRNN/LSTM/GRU + cells) over
the reference's cuDNN rnn kernel (`paddle/phi/kernels/gpu/rnn_kernel.cu`).
TPU-native: the whole time loop is ONE dispatched op built on `jax.lax.scan`
— XLA compiles the recurrence; no per-step python dispatch, no cuDNN.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..layer_base import Layer
from .. import initializer as I
from ...core import dispatch
from ...ops._helpers import as_tensor
from ...ops import manipulation as manip
from ...core.tensor import Tensor


def _cell_step(mode, w_ih, w_hh, b_ih, b_hh, x_t, h, c=None):
    if mode == "GRU":
        # paddle gate order: update(z), reset(r), candidate(c)
        xg = x_t @ w_ih.T + (b_ih if b_ih is not None else 0.0)
        hg = h @ w_hh.T + (b_hh if b_hh is not None else 0.0)
        xz, xr, xc = jnp.split(xg, 3, axis=-1)
        hz, hr, hc = jnp.split(hg, 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        cand = jnp.tanh(xc + r * hc)
        h_new = (1.0 - z) * cand + z * h
        return h_new, None
    gates = x_t @ w_ih.T + h @ w_hh.T
    if b_ih is not None:
        gates = gates + b_ih + b_hh
    if mode == "LSTM":
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return h_new, c_new
    # SimpleRNN (tanh or relu)
    act = jnp.tanh if mode == "RNN_TANH" else jax.nn.relu
    return act(gates), None


class RNNCellBase(Layer):
    def __init__(self, input_size, hidden_size, n_gates, mode,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self._mode = mode
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [n_gates * hidden_size, input_size], weight_ih_attr,
            default_initializer=u)
        self.weight_hh = self.create_parameter(
            [n_gates * hidden_size, hidden_size], weight_hh_attr,
            default_initializer=u)
        self.bias_ih = self.create_parameter(
            [n_gates * hidden_size], bias_ih_attr, is_bias=True,
            default_initializer=u)
        self.bias_hh = self.create_parameter(
            [n_gates * hidden_size], bias_hh_attr, is_bias=True,
            default_initializer=u)

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        batch = as_tensor(batch_ref).shape[batch_dim_idx]
        from ...ops.creation import full
        if self._mode == "LSTM":
            return (full([batch, self.hidden_size], init_value, "float32"),
                    full([batch, self.hidden_size], init_value, "float32"))
        return full([batch, self.hidden_size], init_value, "float32")


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__(input_size, hidden_size, 4, "LSTM", weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h, c = states
        args = [as_tensor(inputs), as_tensor(h), as_tensor(c),
                self.weight_ih, self.weight_hh]
        has_bias = self.bias_ih is not None
        if has_bias:
            args += [self.bias_ih, self.bias_hh]

        def _fn(x, h0, c0, wih, whh, *bs):
            bih, bhh = (bs[0], bs[1]) if bs else (None, None)
            h1, c1 = _cell_step("LSTM", wih, whh, bih, bhh, x, h0, c0)
            return h1, c1
        h1, c1 = dispatch.apply("lstm_cell", _fn, tuple(args))
        return h1, (h1, c1)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__(input_size, hidden_size, 3, "GRU", weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        args = [as_tensor(inputs), as_tensor(states), self.weight_ih,
                self.weight_hh]
        has_bias = self.bias_ih is not None
        if has_bias:
            args += [self.bias_ih, self.bias_hh]

        def _fn(x, h0, wih, whh, *bs):
            bih, bhh = (bs[0], bs[1]) if bs else (None, None)
            h1, _ = _cell_step("GRU", wih, whh, bih, bhh, x, h0)
            return h1
        h1 = dispatch.apply("gru_cell", _fn, tuple(args))
        return h1, h1


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        mode = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        super().__init__(input_size, hidden_size, 1, mode, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        args = [as_tensor(inputs), as_tensor(states), self.weight_ih,
                self.weight_hh]
        if self.bias_ih is not None:
            args += [self.bias_ih, self.bias_hh]
        mode = self._mode

        def _fn(x, h0, wih, whh, *bs):
            bih, bhh = (bs[0], bs[1]) if bs else (None, None)
            h1, _ = _cell_step(mode, wih, whh, bih, bhh, x, h0)
            return h1
        h1 = dispatch.apply("rnn_cell", _fn, tuple(args))
        return h1, h1


class _RNNBase(Layer):
    """Multi-layer (optionally bidirectional) recurrence as one scan op."""

    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirect = direction in ("bidirect", "bidirectional")
        n_dir = 2 if self.bidirect else 1
        n_gates = {"LSTM": 4, "GRU": 3, "RNN_TANH": 1, "RNN_RELU": 1}[mode]
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self._weights = []  # (wih, whh, bih, bhh) per (layer, dir)
        for layer in range(num_layers):
            for d in range(n_dir):
                in_sz = input_size if layer == 0 else hidden_size * n_dir
                wih = self.create_parameter([n_gates * hidden_size, in_sz],
                                            weight_ih_attr,
                                            default_initializer=u)
                whh = self.create_parameter(
                    [n_gates * hidden_size, hidden_size], weight_hh_attr,
                    default_initializer=u)
                bih = self.create_parameter([n_gates * hidden_size],
                                            bias_ih_attr, is_bias=True,
                                            default_initializer=u)
                bhh = self.create_parameter([n_gates * hidden_size],
                                            bias_hh_attr, is_bias=True,
                                            default_initializer=u)
                sfx = f"_l{layer}" + ("_reverse" if d else "")
                self.add_parameter(f"weight_ih{sfx}", wih)
                self.add_parameter(f"weight_hh{sfx}", whh)
                self.add_parameter(f"bias_ih{sfx}", bih)
                self.add_parameter(f"bias_hh{sfx}", bhh)
                self._weights.append((wih, whh, bih, bhh))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        x = as_tensor(inputs)
        n_dir = 2 if self.bidirect else 1
        time_major = self.time_major
        mode = self.mode
        num_layers = self.num_layers
        hidden = self.hidden_size
        batch = x.shape[0] if not time_major else x.shape[1]
        is_lstm = mode == "LSTM"

        from ...ops.creation import zeros
        if initial_states is None:
            h0 = zeros([num_layers * n_dir, batch, hidden], "float32")
            c0 = zeros([num_layers * n_dir, batch, hidden], "float32")
            initial_states = (h0, c0) if is_lstm else h0
        if is_lstm:
            h0, c0 = initial_states
        else:
            h0, c0 = initial_states, None

        flat_weights = [w for group in self._weights for w in group]
        args = [x, as_tensor(h0)] + ([as_tensor(c0)] if is_lstm else []) \
            + flat_weights
        n_state = 2 if is_lstm else 1

        def _fn(xa, h0a, *rest):
            if is_lstm:
                c0a, weights = rest[0], rest[1:]
            else:
                c0a, weights = None, rest
            seq = xa if time_major else jnp.swapaxes(xa, 0, 1)  # [T,B,I]
            out = seq
            h_finals, c_finals = [], []
            for layer in range(num_layers):
                dir_outs = []
                for d in range(n_dir):
                    w_off = (layer * n_dir + d) * 4
                    wih, whh, bih, bhh = weights[w_off:w_off + 4]
                    idx = layer * n_dir + d
                    h_init = h0a[idx]
                    c_init = c0a[idx] if is_lstm else jnp.zeros_like(h_init)

                    def step(carry, x_t, wih=wih, whh=whh, bih=bih, bhh=bhh):
                        h, c = carry
                        h1, c1 = _cell_step(mode, wih, whh, bih, bhh,
                                            x_t, h, c)
                        if c1 is None:
                            c1 = c
                        return (h1, c1), h1
                    seq_d = jnp.flip(out, 0) if d == 1 else out
                    (h_f, c_f), ys = jax.lax.scan(step, (h_init, c_init),
                                                  seq_d)
                    if d == 1:
                        ys = jnp.flip(ys, 0)
                    dir_outs.append(ys)
                    h_finals.append(h_f)
                    c_finals.append(c_f)
                out = jnp.concatenate(dir_outs, axis=-1) if n_dir == 2 \
                    else dir_outs[0]
            y = out if time_major else jnp.swapaxes(out, 0, 1)
            h_all = jnp.stack(h_finals)
            if is_lstm:
                return y, h_all, jnp.stack(c_finals)
            return y, h_all

        outs = dispatch.apply(f"rnn_{mode.lower()}", _fn, tuple(args))
        if is_lstm:
            y, h_n, c_n = outs
            return y, (h_n, c_n)
        y, h_n = outs
        return y, h_n


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kw):
        mode = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        super().__init__(mode, input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kw)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kw):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kw)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kw):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kw)


class RNN(Layer):
    """Wrapper running a cell over time (paddle.nn.RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        x = as_tensor(inputs)
        steps = x.shape[0] if self.time_major else x.shape[1]
        outputs = []
        states = initial_states
        order = range(steps - 1, -1, -1) if self.is_reverse else range(steps)
        for t in order:
            x_t = x[t] if self.time_major else x[:, t]
            out, states = self.cell(x_t, states)
            outputs.append(out)
        if self.is_reverse:
            outputs = outputs[::-1]
        y = manip.stack(outputs, axis=0 if self.time_major else 1)
        return y, states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        st_fw, st_bw = (initial_states if initial_states is not None
                        else (None, None))
        y_fw, s_fw = self.rnn_fw(inputs, st_fw)
        y_bw, s_bw = self.rnn_bw(inputs, st_bw)
        y = manip.concat([y_fw, y_bw], axis=-1)
        return y, (s_fw, s_bw)
