"""Common layers: Linear, Embedding, Dropout, Flatten, …

Parity: `python/paddle/nn/layer/common.py`.
"""
from __future__ import annotations

import numpy as np

from ..layer_base import Layer
from ..param_attr import ParamAttr
from .. import initializer as I
from .. import functional as F
from ...core import dtype as dtype_mod
from ...ops import manipulation as manip


class Identity(Layer):
    def forward(self, x):
        return x


class Linear(Layer):
    """paddle.nn.Linear: weight [in_features, out_features]."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr)
        self.bias = self.create_parameter(
            shape=[out_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return (f"in_features={self._in_features}, "
                f"out_features={self._out_features}")


class Embedding(Layer):
    """paddle.nn.Embedding: weight [num_embeddings, embedding_dim]."""

    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = padding_idx if padding_idx is None or \
            padding_idx >= 0 else num_embeddings + padding_idx
        self.weight = self.create_parameter(
            shape=[num_embeddings, embedding_dim], attr=weight_attr)
        if self._padding_idx is not None:
            w = np.asarray(self.weight.numpy())
            w[self._padding_idx] = 0
            self.weight.set_value(w)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx)

    def extra_repr(self):
        return (f"num_embeddings={self._num_embeddings}, "
                f"embedding_dim={self._embedding_dim}")


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, self.p, axis=self.axis, training=self.training,
                         mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, self.p, training=self.training,
                           data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, training=self.training)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        return manip.flatten(x, self.start_axis, self.stop_axis)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners,
                             data_format=self.data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class Pad1D(Layer):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCL", name=None):
        super().__init__()
        self.padding, self.mode, self.value = padding, mode, value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value,
                     self.data_format)


class Pad2D(Pad1D):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCHW", name=None):
        super().__init__(padding, mode, value, data_format, name)


class Pad3D(Pad1D):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCDHW", name=None):
        super().__init__(padding, mode, value, data_format, name)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self.kernel_sizes = kernel_sizes
        self.strides = strides
        self.paddings = paddings
        self.dilations = dilations

    def forward(self, x):
        return F.unfold(x, self.kernel_sizes, self.strides, self.paddings,
                        self.dilations)
