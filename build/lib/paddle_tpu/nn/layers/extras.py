"""Round-2 layer additions.

Parity: the remaining `python/paddle/nn/layer/*` classes — Bilinear,
CTCLoss, ChannelShuffle, Fold, HSigmoidLoss, LayerDict, MaxUnPool1/2/3D,
MultiLabelSoftMarginLoss, PairwiseDistance, PixelUnshuffle, RReLU,
SoftMarginLoss, Softmax2D, ThresholdedReLU, TripletMarginWithDistanceLoss,
UpsamplingBilinear2D/Nearest2D, ZeroPad2D.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..layer_base import Layer
from ..initializer import XavierUniform
from ... import ops
from ...core.tensor import Tensor
from .. import functional as F


def _reduce_tensor(loss, reduction):
    """Tensor-level reduction (the array-level _reduce_loss runs inside
    dispatched fns; this one composes eager Tensor ops)."""
    if reduction == "mean":
        return ops.mean(loss)
    if reduction == "sum":
        return ops.sum(loss)
    return loss


class Bilinear(Layer):
    """out = x1 . W . x2 + b (per output feature)."""

    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [out_features, in1_features, in2_features],
            attr=weight_attr, default_initializer=XavierUniform())
        self.bias = self.create_parameter(
            [out_features], attr=bias_attr, is_bias=True)

    def forward(self, x1, x2):
        from ...core import dispatch

        def f(a, b, w, bias):
            return jnp.einsum("bi,oij,bj->bo", a, w, b) + bias

        from ...ops._helpers import as_tensor
        return dispatch.apply(
            "bilinear", f, (as_tensor(x1), as_tensor(x2), self.weight,
                            self.bias))


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank = blank
        self.reduction = reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths,
                          label_lengths, blank=self.blank,
                          reduction=self.reduction,
                          norm_by_times=norm_by_times)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        assert data_format == "NCHW"
        self.groups = groups

    def forward(self, x):
        n, c, h, w = x.shape
        g = self.groups
        x = ops.reshape(x, [n, g, c // g, h, w])
        x = ops.transpose(x, [0, 2, 1, 3, 4])
        return ops.reshape(x, [n, c, h, w])


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.args = (output_sizes, kernel_sizes, strides, paddings,
                     dilations)

    def forward(self, x):
        return F.fold(x, *self.args)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.args)


class HSigmoidLoss(Layer):
    """Hierarchical sigmoid over a complete binary tree of classes
    (`paddle/phi/kernels/hsigmoid_loss_kernel.h` default-tree mode):
    path/code tables precomputed per class at init."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        assert not is_custom, "custom trees: pass path tables directly"
        self.num_classes = num_classes
        n_nodes = num_classes - 1  # internal nodes of a complete tree
        self.weight = self.create_parameter(
            [n_nodes, feature_size], attr=weight_attr,
            default_initializer=XavierUniform())
        self.bias = self.create_parameter([n_nodes], attr=bias_attr,
                                          is_bias=True)
        # heap numbering: classes are leaves [num_classes-1, 2*nc-2];
        # internal nodes [0, nc-2]; parent(i) = (i-1)//2
        depth = int(np.ceil(np.log2(max(num_classes, 2)))) + 1
        paths = np.zeros((num_classes, depth), np.int32)
        codes = np.zeros((num_classes, depth), np.float32)
        lengths = np.zeros(num_classes, np.int32)
        for cls in range(num_classes):
            node = cls + num_classes - 1
            seq = []
            while node > 0:
                parent = (node - 1) // 2
                seq.append((parent, 1.0 if node == 2 * parent + 2
                            else 0.0))
                node = parent
            seq.reverse()
            lengths[cls] = len(seq)
            for i, (p, c) in enumerate(seq):
                paths[cls, i] = p
                codes[cls, i] = c
        self._paths = jnp.asarray(paths)
        self._codes = jnp.asarray(codes)
        self._lens = jnp.asarray(lengths)

    def forward(self, input, label):
        from ...core import dispatch
        from ...ops._helpers import as_tensor
        paths, codes, lens = self._paths, self._codes, self._lens

        def f(x, lab, w, b):
            lab = lab.reshape(-1)
            pth = paths[lab]                   # [B, D]
            cde = codes[lab]                   # [B, D]
            msk = (jnp.arange(paths.shape[1])[None, :]
                   < lens[lab][:, None]).astype(x.dtype)
            logits = jnp.einsum("bf,bdf->bd", x, w[pth]) + b[pth]
            # code 1 -> right child: sigmoid(logit); 0 -> 1-sigmoid
            logp = -jnp.logaddexp(0.0, -logits) * cde \
                   + -jnp.logaddexp(0.0, logits) * (1.0 - cde)
            return -(logp * msk).sum(-1, keepdims=True)

        return dispatch.apply(
            "hsigmoid_loss", f,
            (as_tensor(input), as_tensor(label), self.weight, self.bias))


class LayerDict(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        self._keys = []
        if sublayers:
            self.update(sublayers)

    def __getitem__(self, key):
        return getattr(self, key)

    def __setitem__(self, key, layer):
        if key not in self._keys:
            self._keys.append(key)
        self.add_sublayer(key, layer)

    def __delitem__(self, key):
        self._keys.remove(key)
        delattr(self, key)

    def __len__(self):
        return len(self._keys)

    def __iter__(self):
        return iter(self._keys)

    def keys(self):
        return list(self._keys)

    def values(self):
        return [self[k] for k in self._keys]

    def items(self):
        return [(k, self[k]) for k in self._keys]

    def update(self, sublayers):
        pairs = sublayers.items() if isinstance(sublayers, dict) \
            else sublayers
        for k, v in pairs:
            self[k] = v


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, output_size)

    def forward(self, x, indices):
        k, s, p, osize = self.args
        return F.max_unpool2d(x, indices, k, stride=s, padding=p,
                              output_size=osize)


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, output_size)

    def forward(self, x, indices):
        k, s, p, osize = self.args
        x4 = ops.unsqueeze(x, 2)          # [N,C,1,L]
        i4 = ops.unsqueeze(indices, 2)
        o4 = None if osize is None else [1, osize[-1]]
        out = F.max_unpool2d(
            x4, i4, (1, k), stride=(1, s if s is not None else k),
            padding=(0, p), output_size=o4)
        return ops.squeeze(out, 2)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        from ...ops._helpers import as_tensor
        from ...core import dispatch

        w = None if self.weight is None else as_tensor(self.weight)

        def f(x, y, *rest):
            logsig = -jnp.logaddexp(0.0, -x)
            logsig_neg = -jnp.logaddexp(0.0, x)
            per = -(y * logsig + (1 - y) * logsig_neg)
            if rest:
                per = per * rest[0]
            return per.mean(-1)

        args = (as_tensor(input), as_tensor(label)) + \
            ((w,) if w is not None else ())
        return _reduce_tensor(
            dispatch.apply("multilabel_soft_margin", f, args),
            self.reduction)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.eps, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        from ...core import dispatch
        from ...ops._helpers import as_tensor
        p, eps, keep = self.p, self.eps, self.keepdim

        def f(a, b):
            d = a - b + eps
            return jnp.sum(jnp.abs(d) ** p, axis=-1,
                           keepdims=keep) ** (1.0 / p)

        return dispatch.apply("pairwise_distance", f,
                              (as_tensor(x), as_tensor(y)))


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        assert data_format == "NCHW"
        self.r = downscale_factor

    def forward(self, x):
        n, c, h, w = x.shape
        r = self.r
        x = ops.reshape(x, [n, c, h // r, r, w // r, r])
        x = ops.transpose(x, [0, 1, 3, 5, 2, 4])
        return ops.reshape(x, [n, c * r * r, h // r, w // r])


class RReLU(Layer):
    """Randomized leaky ReLU: slope ~ U[lower, upper] in train, mean
    slope in eval."""

    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self.lower, self.upper = lower, upper

    def forward(self, x):
        from ...core import dispatch, random as rng_mod
        from ...ops._helpers import as_tensor
        lower, upper = self.lower, self.upper
        if self.training:
            import jax
            key = rng_mod.next_key()

            def f(a):
                slope = jax.random.uniform(key, a.shape, jnp.float32,
                                           lower, upper).astype(a.dtype)
                return jnp.where(a >= 0, a, a * slope)
        else:
            mean = (lower + upper) / 2.0

            def f(a):
                return jnp.where(a >= 0, a, a * mean)

        return dispatch.apply("rrelu", f, (as_tensor(x),))


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        from ...core import dispatch
        from ...ops._helpers import as_tensor

        def f(x, y):
            return jnp.logaddexp(0.0, -y * x)

        return _reduce_tensor(
            dispatch.apply("soft_margin", f,
                           (as_tensor(input), as_tensor(label))),
            self.reduction)


class Softmax2D(Layer):
    """Softmax over the channel axis of NCHW."""

    def forward(self, x):
        return F.softmax(x, axis=-3)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        from ...core import dispatch
        from ...ops._helpers import as_tensor
        th = self.threshold

        def f(a):
            return jnp.where(a > th, a, 0.0).astype(a.dtype)

        return dispatch.apply("thresholded_relu", f, (as_tensor(x),))


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.dist = distance_function or PairwiseDistance(2.0)
        self.margin = margin
        self.swap = swap
        self.reduction = reduction

    def forward(self, input, positive, negative):
        d_pos = self.dist(input, positive)
        d_neg = self.dist(input, negative)
        if self.swap:
            d_pn = self.dist(positive, negative)
            d_neg = ops.minimum(d_neg, d_pn)
        loss = ops.maximum(d_pos - d_neg + self.margin,
                           ops.zeros_like(d_pos))
        return _reduce_tensor(loss, self.reduction)


class UpsamplingBilinear2D(Layer):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size, self.sf, self.df = size, scale_factor, data_format

    def forward(self, x):
        return F.interpolate(x, size=self.size, scale_factor=self.sf,
                             mode="bilinear", align_corners=True,
                             data_format=self.df)


class UpsamplingNearest2D(UpsamplingBilinear2D):
    def forward(self, x):
        return F.interpolate(x, size=self.size, scale_factor=self.sf,
                             mode="nearest", data_format=self.df)


class ZeroPad2D(Layer):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__()
        self.padding = padding
        self.df = data_format

    def forward(self, x):
        return F.pad(x, self.padding, mode="constant", value=0.0,
                     data_format=self.df)
