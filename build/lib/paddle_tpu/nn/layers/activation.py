"""Activation layers — `python/paddle/nn/layer/activation.py` parity."""
from __future__ import annotations

from ..layer_base import Layer
from .. import functional as F
from .. import initializer as I


def _simple(name, fname=None, **fixed):
    fname = fname or name.lower()

    class _Act(Layer):
        def __init__(self, name=None, **kw):
            super().__init__()
            self._kw = {**fixed, **kw}

        def forward(self, x):
            return getattr(F, fname)(x, **self._kw)
    _Act.__name__ = name
    _Act.__qualname__ = name
    return _Act


ReLU = _simple("ReLU", "relu")
ReLU6 = _simple("ReLU6", "relu6")
Sigmoid = _simple("Sigmoid", "sigmoid")
Tanh = _simple("Tanh", "tanh")
Silu = _simple("Silu", "silu")
Swish = _simple("Swish", "swish")
Mish = _simple("Mish", "mish")
GELU = _simple("GELU", "gelu")
SELU = _simple("SELU", "selu")
CELU = _simple("CELU", "celu")
Hardswish = _simple("Hardswish", "hardswish")
Hardsigmoid = _simple("Hardsigmoid", "hardsigmoid")
Hardshrink = _simple("Hardshrink", "hardshrink")
Softshrink = _simple("Softshrink", "softshrink")
Tanhshrink = _simple("Tanhshrink", "tanhshrink")
Softplus = _simple("Softplus", "softplus")
Softsign = _simple("Softsign", "softsign")
LogSigmoid = _simple("LogSigmoid", "log_sigmoid")


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self._negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self._negative_slope)


class ELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return F.elu(x, self._alpha)


class Hardtanh(Layer):
    def __init__(self, min=-1.0, max=1.0, name=None):
        super().__init__()
        self._min, self._max = min, max

    def forward(self, x):
        return F.hardtanh(x, self._min, self._max)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            shape=[num_parameters], attr=weight_attr,
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.softmax(x, self._axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.log_softmax(x, self._axis)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self._groups, self._axis = groups, axis

    def forward(self, x):
        return F.maxout(x, self._groups, self._axis)
