"""Conv layers — `python/paddle/nn/layer/conv.py` parity.

Weight layout [out_c, in_c/groups, *k] (transpose: [in_c, out_c/groups, *k]),
matching the reference so state_dicts port over.
"""
from __future__ import annotations

import numpy as np

from ..layer_base import Layer
from .. import initializer as I
from .. import functional as F
from .conv_utils import _ntuple


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride,
                 padding, dilation, groups, padding_mode, weight_attr,
                 bias_attr, data_format, dims, transposed=False,
                 output_padding=0):
        super().__init__()
        self._in_channels = in_channels
        self._out_channels = out_channels
        self._kernel_size = _ntuple(kernel_size, dims)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._data_format = data_format
        self._transposed = transposed
        self._output_padding = output_padding
        if transposed:
            w_shape = [in_channels, out_channels // groups,
                       *self._kernel_size]
        else:
            w_shape = [out_channels, in_channels // groups,
                       *self._kernel_size]
        fan_in = in_channels // groups * int(np.prod(self._kernel_size))
        self.weight = self.create_parameter(
            shape=w_shape, attr=weight_attr,
            default_initializer=None)
        if weight_attr is None or (
                getattr(weight_attr, "initializer", None) is None
                and not isinstance(weight_attr, I.Initializer)):
            # paddle conv default: Normal(0, sqrt(2/fan_out))-like; use
            # KaimingUniform as nn.Conv2D does via XavierUniform default
            pass
        self.bias = self.create_parameter(
            shape=[out_channels], attr=bias_attr, is_bias=True)

    def extra_repr(self):
        return (f"{self._in_channels}, {self._out_channels}, "
                f"kernel_size={self._kernel_size}, stride={self._stride}")


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format, 1)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups,
                        self._data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format, 2)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups,
                        self._data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format, 3)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups,
                        self._data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, 1, transposed=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation,
                                  output_size, self._data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, 2, transposed=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation,
                                  output_size, self._data_format)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, 3, transposed=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation,
                                  output_size, self._data_format)
