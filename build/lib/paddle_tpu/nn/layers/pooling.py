"""Pooling layers — `python/paddle/nn/layer/pooling.py` parity."""
from __future__ import annotations

from ..layer_base import Layer
from .. import functional as F


class _Pool(Layer):
    def __init__(self, kernel_size=None, stride=None, padding=0,
                 ceil_mode=False, data_format=None, **kw):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        self._data_format = data_format
        self._kw = kw


class MaxPool1D(_Pool):
    def forward(self, x):
        return F.max_pool1d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode)


class MaxPool2D(_Pool):
    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode,
                            data_format=self._data_format or "NCHW")


class MaxPool3D(_Pool):
    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode)


class AvgPool1D(_Pool):
    def forward(self, x):
        return F.avg_pool1d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode)


class AvgPool2D(_Pool):
    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode,
                            data_format=self._data_format or "NCHW")


class AvgPool3D(_Pool):
    def forward(self, x):
        return F.avg_pool3d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self._output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self._output_size)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self._output_size = output_size
        self._data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self._output_size,
                                     self._data_format)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self._output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self._output_size)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self._output_size)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self._output_size)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self._output_size)
