"""Norm layers — `python/paddle/nn/layer/norm.py` parity."""
from __future__ import annotations

import numpy as np

from ..layer_base import Layer
from .. import initializer as I
from .. import functional as F
from ...core.tensor import Tensor


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                shape=[num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                shape=[num_features], attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", Tensor(np.zeros(num_features,
                                                      np.float32)))
        self.register_buffer("_variance", Tensor(np.ones(num_features,
                                                         np.float32)))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum,
            epsilon=self._epsilon, data_format=self._data_format,
            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}"


class BatchNorm(_BatchNormBase):
    """Legacy `paddle.nn.BatchNorm` (fluid.dygraph.BatchNorm) signature."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5,
                 param_attr=None, bias_attr=None, dtype="float32",
                 data_layout="NCHW", in_place=False, moving_mean_name=None,
                 moving_variance_name=None, do_model_average_for_mean_and_var=True,
                 use_global_stats=False, trainable_statistics=False):
        super().__init__(num_channels, momentum, epsilon, param_attr,
                         bias_attr, data_layout,
                         use_global_stats or None)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            out = getattr(F, self._act)(out)
        return out


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """Parity: `python/paddle/nn/layer/norm.py` SyncBatchNorm
    (sync_batch_norm op). Under pjit/GSPMD the batch stats reduce happens
    inside the compiled step via sharding; cross-replica sync in the eager
    DP path psums the batch stats over the dp group."""

    def forward(self, x):
        from ...parallel import env as dist_env
        if self.training and dist_env.get_world_size() > 1:
            # eager cross-replica stats: psum mean/var over dp group
            from ...parallel import collective as C
            from ... import ops
            axes = [i for i in range(x.ndim)
                    if i != (1 if self._data_format == "NCHW" else x.ndim - 1)]
            mean = ops.mean(x, axis=axes)
            mean_sq = ops.mean(ops.multiply(x, x), axis=axes)
            mean = C.all_reduce(mean) / float(dist_env.get_world_size())
            mean_sq = C.all_reduce(mean_sq) / float(dist_env.get_world_size())
            var = mean_sq - mean * mean
            self._mean._data = (self._momentum * self._mean._data
                                + (1 - self._momentum) * mean._data)
            self._variance._data = (self._momentum * self._variance._data
                                    + (1 - self._momentum) * var._data)
            return F.batch_norm(
                x, mean, var, self.weight, self.bias, training=False,
                momentum=self._momentum, epsilon=self._epsilon,
                data_format=self._data_format, use_global_stats=True)
        return super().forward(x)

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(
                layer, SyncBatchNorm):
            out = SyncBatchNorm(layer._num_features, layer._momentum,
                                layer._epsilon,
                                data_format=layer._data_format)
            if layer.weight is not None:
                out.weight.set_value(layer.weight)
            if layer.bias is not None:
                out.bias.set_value(layer.bias)
            out._mean.set_value(layer._mean)
            out._variance.set_value(layer._variance)
        for name, sub in list(layer._sub_layers.items()):
            out._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return out


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                shape=self._normalized_shape, attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                shape=self._normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight,
                            self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._num_channels = num_channels
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                shape=[num_channels], attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                shape=[num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                shape=[num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                shape=[num_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class RMSNorm(Layer):
    """LLM-era RMSNorm (reference exposes fused rms_norm in incubate)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None,
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            shape=[hidden_size], attr=weight_attr,
            default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta,
                                     self.k, self.data_format)
