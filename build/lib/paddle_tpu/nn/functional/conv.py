"""Convolution functionals over `jax.lax.conv_general_dilated`.

Parity: `python/paddle/nn/functional/conv.py` over PHI conv kernels
(`paddle/phi/kernels/gpudnn/conv_kernel.cu` → cuDNN). On TPU the conv
lowers straight onto the MXU; XLA picks the layout/tiling, replacing the
reference's cuDNN algo search + `phi/kernels/autotune/`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core import dispatch
from ...ops._helpers import as_tensor


def _tuple(v, n):
    if isinstance(v, int):
        return (v,) * n
    v = tuple(int(x) for x in v)
    if len(v) == 1:
        return v * n
    return v


def _conv_padding(padding, n, strides=None):
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    if all(isinstance(p, (list, tuple)) for p in padding):
        return [tuple(p) for p in padding]
    raise ValueError(f"bad padding {padding}")


def _conv(x, weight, bias, stride, padding, dilation, groups, n,
          data_format, name):
    x, weight = as_tensor(x), as_tensor(weight)
    from ...ops.linalg import _amp_cast2
    x, weight = _amp_cast2(x, weight)  # O1 cast + O2 dtype harmonization
    strides = _tuple(stride, n)
    dilations = _tuple(dilation, n)
    pad = _conv_padding(padding, n)
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    # layout autotune (imperative/layout_autotune.cc capability): TPU convs
    # run ~20x faster channels-last, so compute internally in N...C and
    # transpose at the facade edges (XLA cancels transposes between
    # stacked channel-first layers)
    spec = {1: ("NWC", "OIW", "NWC"), 2: ("NHWC", "OIHW", "NHWC"),
            3: ("NDHWC", "OIDHW", "NDHWC")}[n]

    def _fn(a, w, *b):
        if not channel_last:
            a = jnp.moveaxis(a, 1, -1)
        dn = jax.lax.conv_dimension_numbers(a.shape, w.shape, spec)
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=strides, padding=pad,
            rhs_dilation=dilations, dimension_numbers=dn,
            feature_group_count=groups,
            preferred_element_type=None)
        if b:
            out = out + b[0].reshape((1,) * (out.ndim - 1)
                                     + (-1,)).astype(out.dtype)
        if not channel_last:
            out = jnp.moveaxis(out, -1, 1)
        return out
    if bias is not None:
        bias = as_tensor(bias)
        return dispatch.apply(f"conv{n}d", _fn, (x, weight, bias))
    return dispatch.apply(f"conv{n}d", _fn, (x, weight))


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    fmt = "NLC" if data_format == "NLC" else "NCL"
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1,
                 fmt, name)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2,
                 data_format, name)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3,
                 data_format, name)


def _conv_transpose(x, weight, bias, stride, padding, output_padding,
                    dilation, groups, n, data_format, output_size, name):
    x, weight = as_tensor(x), as_tensor(weight)
    strides = _tuple(stride, n)
    dilations = _tuple(dilation, n)
    opad = _tuple(output_padding, n) if output_padding is not None \
        else (0,) * n
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    if isinstance(padding, str):
        pads = padding.upper()
    else:
        pads = _conv_padding(padding, n)

    if channel_last:
        spec = {1: ("NWC", "OIW", "NWC"), 2: ("NHWC", "OIHW", "NHWC"),
                3: ("NDHWC", "OIDHW", "NDHWC")}[n]
        ch_in_axis = x.ndim - 1
    else:
        spec = {1: ("NCW", "OIW", "NCW"), 2: ("NCHW", "OIHW", "NCHW"),
                3: ("NCDHW", "OIDHW", "NCDHW")}[n]
        ch_in_axis = 1

    def _one_group(a, w):
        # paddle conv_transpose weight layout: [in_c, out_c, *k];
        # transpose conv = conv with lhs_dilation (fractional stride),
        # flipped kernel, swapped in/out channels.
        k = w.shape[2:]
        if isinstance(pads, str):
            if pads == "SAME":
                pad_t = [(min(dilations[i] * (k[i] - 1), strides[i] - 1
                              + dilations[i] * (k[i] - 1)) // 1,) * 2
                         for i in range(n)]
                pad_t = [(dilations[i] * (k[i] - 1) // 2,
                          dilations[i] * (k[i] - 1)
                          - dilations[i] * (k[i] - 1) // 2)
                         for i in range(n)]
            else:  # VALID
                pad_t = [(dilations[i] * (k[i] - 1),
                          dilations[i] * (k[i] - 1) + opad[i])
                         for i in range(n)]
        else:
            pad_t = []
            for i in range(n):
                lo, hi = pads[i]
                eff_k = dilations[i] * (k[i] - 1)
                pad_t.append((eff_k - lo, eff_k - hi + opad[i]))
        wf = jnp.flip(w, axis=tuple(range(2, 2 + n)))
        wf = jnp.swapaxes(wf, 0, 1)  # [out_c, in_c, *k]
        dn = jax.lax.conv_dimension_numbers(a.shape, wf.shape, spec)
        return jax.lax.conv_general_dilated(
            a, wf, window_strides=(1,) * n, padding=pad_t,
            lhs_dilation=strides, rhs_dilation=dilations,
            dimension_numbers=dn)

    def _fn(a, w, *b):
        if groups == 1:
            out = _one_group(a, w)
        else:
            a_groups = jnp.split(a, groups, axis=ch_in_axis)
            w_groups = jnp.split(w, groups, axis=0)
            out = jnp.concatenate(
                [_one_group(ag, wg) for ag, wg in zip(a_groups, w_groups)],
                axis=ch_in_axis)
        if b:
            bias_shape = [1] * out.ndim
            ch_axis = out.ndim - 1 if channel_last else 1
            bias_shape[ch_axis] = b[0].size
            out = out + b[0].reshape(bias_shape).astype(out.dtype)
        return out
    if bias is not None:
        bias = as_tensor(bias)
        return dispatch.apply(f"conv{n}d_transpose", _fn, (x, weight, bias))
    return dispatch.apply(f"conv{n}d_transpose", _fn, (x, weight))


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 1, data_format, output_size,
                           name)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 2, data_format, output_size,
                           name)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 3, data_format, output_size,
                           name)
