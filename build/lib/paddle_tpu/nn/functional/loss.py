"""Loss functionals.

Parity: `python/paddle/nn/functional/loss.py` over PHI loss kernels
(`paddle/phi/kernels/cross_entropy_kernel.h`,
`c_softmax_with_cross_entropy` for the vocab-parallel variant — that one
lives in parallel/mp_ops.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core import dispatch
from ...core.tensor import Tensor
from ...ops._helpers import as_tensor


def _reduce_loss(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    input, label = as_tensor(input), as_tensor(label)
    inputs = [input, label]
    if weight is not None:
        inputs.append(as_tensor(weight))

    def _fn(logits, lab, *w):
        lg = logits.astype(jnp.float32)
        if use_softmax:
            logp = jax.nn.log_softmax(lg, axis=axis)
        else:
            logp = jnp.log(jnp.maximum(lg, 1e-30))
        n_classes = logp.shape[axis]
        if soft_label:
            tgt = lab.astype(jnp.float32)
            if label_smoothing > 0:
                tgt = (1 - label_smoothing) * tgt + label_smoothing / n_classes
            loss = -jnp.sum(tgt * logp, axis=axis)
            valid = None
        else:
            lab_i = lab.astype(jnp.int32)
            if lab_i.ndim == logp.ndim:  # [N, ..., 1]
                lab_i = jnp.squeeze(lab_i, axis=axis)
            valid = lab_i != ignore_index
            safe_lab = jnp.where(valid, lab_i, 0)
            picked = jnp.take_along_axis(
                logp, jnp.expand_dims(safe_lab, axis), axis=axis)
            picked = jnp.squeeze(picked, axis=axis)
            if label_smoothing > 0:
                smooth = jnp.mean(logp, axis=axis)
                picked = (1 - label_smoothing) * picked \
                    + label_smoothing * smooth
            loss = -jnp.where(valid, picked, 0.0)
            if w:
                wgt = jnp.take(w[0].astype(jnp.float32), safe_lab)
                loss = loss * jnp.where(valid, wgt, 0.0)
        if reduction == "mean":
            if valid is not None:
                if w:
                    wgt = jnp.take(w[0].astype(jnp.float32),
                                   jnp.where(valid, lab_i, 0))
                    denom = jnp.maximum(
                        jnp.sum(jnp.where(valid, wgt, 0.0)), 1e-12)
                else:
                    denom = jnp.maximum(jnp.sum(valid.astype(jnp.float32)),
                                        1.0)
                return jnp.sum(loss) / denom
            return jnp.mean(loss)
        return _reduce_loss(loss, reduction)
    return dispatch.apply("cross_entropy", _fn, tuple(inputs))


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none",
                         axis=axis)
    from .activation import softmax as _softmax
    loss = loss.astype(as_tensor(logits).dtype)
    if loss.ndim < as_tensor(logits).ndim:
        from ...ops.manipulation import unsqueeze
        loss = unsqueeze(loss, axis)
    if return_softmax:
        return loss, _softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    input, label = as_tensor(input), as_tensor(label)
    inputs = [input, label]
    if weight is not None:
        inputs.append(as_tensor(weight))

    def _fn(logp, lab, *w):
        lab_i = lab.astype(jnp.int32)
        valid = lab_i != ignore_index
        safe = jnp.where(valid, lab_i, 0)
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(safe, 1), axis=1).squeeze(1)
        loss = -jnp.where(valid, picked, 0.0)
        if w:
            wgt = jnp.take(w[0], safe)
            loss = loss * jnp.where(valid, wgt, 0.0)
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(
                    jnp.sum(jnp.where(valid, wgt, 0.0)), 1e-12)
        return _reduce_loss(loss, reduction)
    return dispatch.apply("nll_loss", _fn, tuple(inputs))


def mse_loss(input, label, reduction="mean", name=None):
    input, label = as_tensor(input), as_tensor(label)

    def _fn(a, b):
        return _reduce_loss((a - b) ** 2, reduction)
    return dispatch.apply("mse_loss", _fn, (input, label))


def l1_loss(input, label, reduction="mean", name=None):
    input, label = as_tensor(input), as_tensor(label)

    def _fn(a, b):
        return _reduce_loss(jnp.abs(a - b), reduction)
    return dispatch.apply("l1_loss", _fn, (input, label))


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    input, label = as_tensor(input), as_tensor(label)

    def _fn(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        return _reduce_loss(loss, reduction)
    return dispatch.apply("smooth_l1_loss", _fn, (input, label))


def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         name=None):
    input, label = as_tensor(input), as_tensor(label)
    inputs = [input, label]
    if weight is not None:
        inputs.append(as_tensor(weight))

    def _fn(p, y, *w):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if w:
            loss = loss * w[0]
        return _reduce_loss(loss, reduction)
    return dispatch.apply("bce", _fn, tuple(inputs))


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    logit, label = as_tensor(logit), as_tensor(label)
    inputs = [logit, label]
    w_idx = pw_idx = None
    if weight is not None:
        w_idx = len(inputs)
        inputs.append(as_tensor(weight))
    if pos_weight is not None:
        pw_idx = len(inputs)
        inputs.append(as_tensor(pos_weight))

    def _fn(z, y, *rest):
        max_val = jnp.maximum(-z, 0.0)
        if pw_idx is not None:
            pw = rest[pw_idx - 2]
            log_w = (pw - 1.0) * y + 1.0
            loss = (1 - y) * z + log_w * (
                jnp.log1p(jnp.exp(-jnp.abs(z))) + max_val)
        else:
            loss = (1 - y) * z + jnp.log1p(jnp.exp(-jnp.abs(z))) + max_val
        if w_idx is not None:
            loss = loss * rest[w_idx - 2]
        return _reduce_loss(loss, reduction)
    return dispatch.apply("bce_with_logits", _fn, tuple(inputs))


def kl_div(input, label, reduction="mean", name=None):
    input, label = as_tensor(input), as_tensor(label)

    def _fn(logp, tgt):
        loss = tgt * (jnp.log(jnp.maximum(tgt, 1e-30)) - logp)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce_loss(loss, reduction)
    return dispatch.apply("kl_div", _fn, (input, label))


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    input, other, label = as_tensor(input), as_tensor(other), \
        as_tensor(label)

    def _fn(a, b, y):
        return _reduce_loss(jnp.maximum(0.0, -y * (a - b) + margin),
                            reduction)
    return dispatch.apply("margin_ranking", _fn, (input, other, label))


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean",
                         name=None):
    input, label = as_tensor(input), as_tensor(label)

    def _fn(a, y):
        loss = jnp.where(y == 1, a, jnp.maximum(0.0, margin - a))
        return _reduce_loss(loss, reduction)
    return dispatch.apply("hinge_embedding", _fn, (input, label))


def cosine_embedding_loss(input1, input2, label, margin=0, reduction="mean",
                          name=None):
    input1, input2, label = as_tensor(input1), as_tensor(input2), \
        as_tensor(label)

    def _fn(a, b, y):
        cos = jnp.sum(a * b, axis=-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce_loss(loss, reduction)
    return dispatch.apply("cosine_embedding", _fn, (input1, input2, label))


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean",
                        name=None):
    input, positive, negative = (as_tensor(input), as_tensor(positive),
                                 as_tensor(negative))

    def _fn(a, pos, neg):
        def dist(u, v):
            return jnp.sum(jnp.abs(u - v) ** p + epsilon,
                           axis=-1) ** (1.0 / p)
        d_pos = dist(a, pos)
        d_neg = dist(a, neg)
        if swap:
            d_neg = jnp.minimum(d_neg, dist(pos, neg))
        return _reduce_loss(jnp.maximum(0.0, d_pos - d_neg + margin),
                            reduction)
    return dispatch.apply("triplet_margin", _fn, (input, positive, negative))


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC (warpctc kernel parity). log_probs [T,B,C] time-major
    unnormalized logits (softmax applied internally, like warpctc);
    labels [B,L]; lengths [B]. Alpha-recursion runs on device via
    optax.ctc_loss."""
    import optax
    from ...core import dispatch

    log_probs = as_tensor(log_probs)
    labels = as_tensor(labels)
    ilen = as_tensor(input_lengths)
    llen = as_tensor(label_lengths)

    def _fn(lp, lab, il, ll):
        logits = jnp.swapaxes(lp, 0, 1)              # [B,T,C]
        B, T, _ = logits.shape
        L = lab.shape[1]
        t_idx = jnp.arange(T)[None, :]
        logit_pad = (t_idx >= il[:, None]).astype(jnp.float32)
        l_idx = jnp.arange(L)[None, :]
        label_pad = (l_idx >= ll[:, None]).astype(jnp.float32)
        per_seq = optax.ctc_loss(logits, logit_pad, lab, label_pad,
                                 blank_id=blank)
        if norm_by_times:
            per_seq = per_seq / jnp.maximum(il.astype(jnp.float32), 1.0)
        if reduction == "mean":
            # reference semantics: each sequence's loss is normalized by
            # its label length before averaging (warpctc convention)
            per_seq = per_seq / jnp.maximum(ll.astype(jnp.float32), 1.0)
        return _reduce_loss(per_seq, reduction)

    return dispatch.apply("ctc_loss", _fn,
                          (log_probs, labels, ilen, llen))


def square_error_cost(input, label):
    input, label = as_tensor(input), as_tensor(label)

    def _fn(a, b):
        return (a - b) ** 2
    return dispatch.apply("square_error_cost", _fn, (input, label))
