"""Weight initializers.

Parity: `python/paddle/fluid/initializer.py` + `python/paddle/nn/initializer/`
(Constant, Normal, TruncatedNormal, Uniform, Xavier*, Kaiming*, Assign).
Each initializer is a callable (shape, dtype) -> jax array; randomness comes
from the global RNG facade so `paddle.seed` reproduces runs.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtype_mod
from ..core import random as rng
from ..core.tensor import Tensor


def _fan_in_out(shape):
    shape = list(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    # conv weight [out_c, in_c, *k] (paddle layout)
    fan_in = shape[1] * receptive
    fan_out = shape[0] * receptive
    return fan_in, fan_out


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, dtype_mod.convert_dtype(dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        dt = dtype_mod.convert_dtype(dtype)
        return jax.random.normal(rng.next_key(), tuple(shape), dt) \
            * self.std + self.mean


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        dt = dtype_mod.convert_dtype(dtype)
        return jax.random.truncated_normal(
            rng.next_key(), -2.0, 2.0, tuple(shape), dt) * self.std + self.mean


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        dt = dtype_mod.convert_dtype(dtype)
        return jax.random.uniform(rng.next_key(), tuple(shape), dt,
                                  minval=self.low, maxval=self.high)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self._fan_in, self._fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fan_in_out(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        fo = self._fan_out if self._fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        dt = dtype_mod.convert_dtype(dtype)
        return jax.random.uniform(rng.next_key(), tuple(shape), dt,
                                  minval=-limit, maxval=limit)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self._fan_in, self._fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fan_in_out(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        fo = self._fan_out if self._fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        dt = dtype_mod.convert_dtype(dtype)
        return jax.random.normal(rng.next_key(), tuple(shape), dt) * std


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self._fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fan_in_out(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        limit = gain * math.sqrt(3.0 / fi)
        dt = dtype_mod.convert_dtype(dtype)
        return jax.random.uniform(rng.next_key(), tuple(shape), dt,
                                  minval=-limit, maxval=limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self._fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype):
        fi, _ = _fan_in_out(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        std = gain / math.sqrt(fi)
        dt = dtype_mod.convert_dtype(dtype)
        return jax.random.normal(rng.next_key(), tuple(shape), dt) * std


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        arr = self.value.numpy() if isinstance(self.value, Tensor) \
            else np.asarray(self.value)
        arr = arr.reshape(shape).astype(dtype_mod.convert_dtype(dtype))
        return jnp.asarray(arr)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype):
        dt = dtype_mod.convert_dtype(dtype)
        w = np.zeros(shape, dtype=np.float32)
        oc, ic = shape[0], shape[1]
        centers = [s // 2 for s in shape[2:]]
        for i in range(min(oc, ic)):
            w[(i, i, *centers)] = 1.0
        return jnp.asarray(w, dt)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        dt = dtype_mod.convert_dtype(dtype)
        return jax.random.orthogonal(
            rng.next_key(), shape[0],
            shape=(), ).astype(dt) * self.gain if len(shape) == 1 else \
            jax.nn.initializers.orthogonal(self.gain)(
                rng.next_key(), tuple(shape), dt)


# paddle.nn.initializer namespace aliases
constant = Constant
normal = Normal
uniform = Uniform
