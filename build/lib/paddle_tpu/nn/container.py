"""Layer containers — `python/paddle/nn/layer/container.py` parity."""
from __future__ import annotations

from .layer_base import Layer
from ..core.tensor import Parameter


class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)) and \
                layers[0] and isinstance(layers[0][0], (list, tuple)):
            # named form: Sequential(('conv', conv), ('relu', relu))
            for name, layer in layers[0]:
                self.add_sublayer(name, layer)
        else:
            if len(layers) == 1 and isinstance(layers[0], (list, tuple)) \
                    and layers[0] and isinstance(layers[0][0], Layer):
                layers = tuple(layers[0])
            for i, layer in enumerate(layers):
                if isinstance(layer, tuple):
                    self.add_sublayer(layer[0], layer[1])
                else:
                    self.add_sublayer(str(i), layer)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return Sequential(*list(self._sub_layers.values())[idx])
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LayerList(list(self._sub_layers.values())[idx])
        return list(self._sub_layers.values())[idx]

    def __setitem__(self, idx, layer):
        key = list(self._sub_layers.keys())[idx]
        self._sub_layers[key] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def append(self, layer):
        self.add_sublayer(str(len(self._sub_layers)), layer)
        return self

    def insert(self, index, layer):
        layers = list(self._sub_layers.values())
        layers.insert(index, layer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l

    def extend(self, layers):
        for l in layers:
            self.append(l)
        return self


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def __getitem__(self, idx):
        return list(self._parameters.values())[idx]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())

    def append(self, parameter):
        self.add_parameter(str(len(self._parameters)), parameter)
        return self
