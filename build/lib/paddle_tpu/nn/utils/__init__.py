"""paddle.nn.utils parity: weight_norm, vector<->parameters, clip helper."""
from __future__ import annotations

import numpy as np

from ...core.tensor import Tensor, Parameter
from ... import ops


def parameters_to_vector(parameters, name=None):
    ts = [ops.reshape(p, [-1]) for p in parameters]
    return ops.concat(ts, axis=0)


def vector_to_parameters(vec, parameters, name=None):
    vec = vec if isinstance(vec, Tensor) else Tensor(vec)
    offset = 0
    arr = vec.numpy()
    for p in parameters:
        n = p.size
        p.set_value(arr[offset:offset + n].reshape(p.shape))
        offset += n


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    params = [p for p in parameters if p.grad is not None]
    if not params:
        return Tensor(np.float32(0.0))
    import jax.numpy as jnp
    if norm_type == float("inf"):
        total = max(float(jnp.max(jnp.abs(p.grad._data))) for p in params)
    else:
        total = float(sum(
            jnp.sum(jnp.abs(p.grad._data.astype(jnp.float32))
                    ** norm_type) for p in params) ** (1.0 / norm_type))
    if error_if_nonfinite and not np.isfinite(total):
        raise RuntimeError("non-finite gradient norm")
    scale = max_norm / (total + 1e-6)
    if scale < 1.0:
        for p in params:
            p.grad._data = p.grad._data * scale
    return Tensor(np.float32(total))


class _WeightNormWrapper:
    """weight_norm(layer): reparameterise weight = g * v / ||v|| via a
    forward pre-hook (paddle.nn.utils.weight_norm parity)."""

    def __init__(self, layer, name, dim):
        self.name = name
        self.dim = dim
        w = getattr(layer, name)
        axes = [i for i in range(w.ndim) if i != dim] if dim is not None \
            else None
        norm = np.sqrt((w.numpy() ** 2).sum(
            axis=tuple(axes) if axes else None, keepdims=True))
        g = Parameter(norm.astype(np.float32).reshape(-1)
                      if dim is not None else norm.astype(np.float32))
        v = Parameter(w.numpy())
        layer.add_parameter(name + "_g", g)
        layer.add_parameter(name + "_v", v)
        # the original weight leaves the parameter registry (it is now a
        # derived value recomputed each forward)
        layer._parameters.pop(name, None)
        self.axes = axes

    def __call__(self, layer, inputs):
        g = getattr(layer, self.name + "_g")
        v = getattr(layer, self.name + "_v")
        vn = ops.sqrt(ops.sum(v * v,
                              axis=self.axes if self.axes else None,
                              keepdim=True)) if self.axes else \
            ops.sqrt(ops.sum(v * v))
        if self.dim is not None:
            shape = [1] * v.ndim
            shape[self.dim] = -1
            gshaped = ops.reshape(g, shape)
        else:
            gshaped = g
        w = v * (gshaped / (vn + 1e-12))
        layer.__dict__[self.name] = w  # visible to forward
        return None


def weight_norm(layer, name="weight", dim=0):
    hook = _WeightNormWrapper(layer, name, dim)
    layer.register_forward_pre_hook(hook)
    # materialise once so the attribute exists before the first call
    hook(layer, ())
    return layer


def remove_weight_norm(layer, name="weight"):
    w = layer.__dict__.pop(name, None)
    if w is not None:
        layer.add_parameter(name, Parameter(w.numpy()))
    for hid, hook in list(layer._forward_pre_hooks.items()):
        if isinstance(hook, _WeightNormWrapper) and hook.name == name:
            layer._forward_pre_hooks.pop(hid)
    layer._parameters.pop(name + "_g", None)
    layer._parameters.pop(name + "_v", None)
    return layer
