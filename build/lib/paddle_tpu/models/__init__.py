from .gpt import (  # noqa: F401
    GPTModel, GPTForPretraining, GPTPretrainingCriterion, GPTDecoderLayer,
    gpt_tiny, gpt2_small, gpt2_medium, gpt3_1p3b,
)
from .bert import (  # noqa: F401
    BertModel, BertForPretraining, BertPretrainingCriterion,
    BertForSequenceClassification, bert_tiny, bert_base, bert_large,
)
