"""Custom C++ ops — paddle.utils.cpp_extension parity.

Parity: `python/paddle/utils/cpp_extension/` (`load(sources)` JIT-compiles
user C++ against `paddle/extension.h` and registers ops). TPU-native: the
user writes a plain C ABI elementwise/host function; `load()` builds it
with g++ and wraps it as a paddle_tpu op via `jax.pure_callback` (host
execution, like the reference's CPU custom kernels) with an optional
custom backward. Device-side custom kernels are written in Pallas instead
(ops/pallas/).

User C signature convention:
    extern "C" void <name>(const float* x, float* out, long long n);
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dispatch
from ..core.tensor import Tensor
from ..ops._helpers import as_tensor

_cache = {}


def _build(source_paths, extra_cxx_flags=None) -> str:
    blob = b""
    for p in source_paths:
        with open(p, "rb") as f:
            blob += f.read()
    blob += " ".join(extra_cxx_flags or []).encode()
    tag = hashlib.sha1(blob).hexdigest()[:16]
    out = os.path.join(tempfile.gettempdir(), f"pt_customop_{tag}.so")
    if not os.path.exists(out):
        cmd = (["g++", "-O3", "-std=c++17", "-shared", "-fPIC"]
               + list(extra_cxx_flags or []) + list(source_paths)
               + ["-o", out])
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"custom op build failed ({' '.join(cmd)}):\n"
                f"{proc.stderr[-4000:]}")
    return out


class CustomOpModule:
    def __init__(self, lib_path, op_names, backward_map=None):
        self._lib = ctypes.CDLL(lib_path)
        self._backward_map = backward_map or {}
        for name in op_names:
            fn = getattr(self._lib, name)
            fn.argtypes = [ctypes.POINTER(ctypes.c_float),
                           ctypes.POINTER(ctypes.c_float),
                           ctypes.c_longlong]
            setattr(self, name, self._make_op(name))

    def _host_call(self, name, arr):
        cfn = getattr(self._lib, name)

        def call(a):
            a = np.ascontiguousarray(a, np.float32)
            out = np.empty_like(a)
            cfn(a.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                a.size)
            return out
        return call(arr)

    def _make_op(self, name):
        bwd_name = self._backward_map.get(name)

        def jax_fn(a):
            return jax.pure_callback(
                lambda x: self._host_call(name, x),
                jax.ShapeDtypeStruct(a.shape, jnp.float32),
                a.astype(jnp.float32))

        if bwd_name is not None:
            @jax.custom_vjp
            def op_core(a):
                return jax_fn(a)

            def fwd(a):
                return jax_fn(a), a

            def bwd(res, g):
                # backward C fn computes d(op)/dx elementwise from x
                dydx = jax.pure_callback(
                    lambda x: self._host_call(bwd_name, x),
                    jax.ShapeDtypeStruct(res.shape, jnp.float32),
                    res.astype(jnp.float32))
                return (g * dydx,)
            op_core.defvjp(fwd, bwd)
            core = op_core
            differentiable = True
        else:
            core = jax_fn
            differentiable = False

        def op(x):
            x = as_tensor(x)
            return dispatch.apply(f"custom_{name}", core, (x,),
                                  differentiable=differentiable)
        op.__name__ = name
        return op


def load(name=None, sources=None, extra_cxx_flags=None, op_names=None,
         backward_map=None, verbose=False, **kwargs):
    """paddle.utils.cpp_extension.load parity (C-ABI convention above).

    op_names: exported C symbols to wrap (default: [name]).
    backward_map: {op: bwd_symbol} where bwd computes elementwise dy/dx.
    """
    assert sources, "sources required"
    srcs = list(sources) if isinstance(sources, (list, tuple)) \
        else [sources]
    lib = _build(srcs, extra_cxx_flags)
    names = op_names or ([name] if name else [])
    assert names, "op_names (or name) required"
    key = (lib, tuple(names),
           tuple(sorted((backward_map or {}).items())))
    if key not in _cache:
        _cache[key] = CustomOpModule(lib, names, backward_map)
    return _cache[key]
