from . import cpp_extension  # noqa: F401
