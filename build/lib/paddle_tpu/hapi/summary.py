"""paddle.summary — parity: `python/paddle/hapi/model_summary.py`."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from .. import ops


def summary(net, input_size=None, dtypes=None):
    """Prints a per-layer table; returns {'total_params', 'trainable_params'}."""
    rows = []
    hooks = []

    def make_hook(name, layer):
        def hook(l, inputs, outputs):
            out = outputs[0] if isinstance(outputs, (list, tuple)) \
                else outputs
            shape = list(out.shape) if isinstance(out, Tensor) else "?"
            n_params = sum(p.size for p in l._parameters.values()
                           if p is not None)
            rows.append((name or l.__class__.__name__,
                         l.__class__.__name__, shape, n_params))
        return hook

    for name, layer in net.named_sublayers():
        if not layer._sub_layers:  # leaves only
            hooks.append(layer.register_forward_post_hook(
                make_hook(name, layer)))

    if input_size is not None:
        if isinstance(input_size, tuple):
            input_size = [input_size]
        dtypes = dtypes or ["float32"] * len(input_size)
        inputs = []
        for shape, dt in zip(input_size, dtypes):
            shape = [s if s and s > 0 else 1 for s in shape]
            if str(dt).startswith("int"):
                inputs.append(Tensor(np.zeros(shape, np.int32)))
            else:
                inputs.append(ops.zeros(shape, dt))
        was_training = net.training
        net.eval()
        try:
            net(*inputs)
        finally:
            if was_training:
                net.train()
    for h in hooks:
        h.remove()

    total = sum(p.size for p in net.parameters())
    trainable = sum(p.size for p in net.parameters()
                    if not p.stop_gradient)
    header = f"{'Layer (type)':<40}{'Output Shape':<24}{'Param #':>12}"
    lines = [header, "=" * len(header)]
    for name, cls, shape, n in rows:
        lines.append(f"{name + ' (' + cls + ')':<40}"
                     f"{str(shape):<24}{n:>12,}")
    lines += ["=" * len(header),
              f"Total params: {total:,}",
              f"Trainable params: {trainable:,}",
              f"Non-trainable params: {total - trainable:,}"]
    print("\n".join(lines))
    return {"total_params": total, "trainable_params": trainable}
