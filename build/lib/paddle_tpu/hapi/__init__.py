from .model import Model, InputSpec  # noqa: F401
from . import callbacks  # noqa: F401
