"""Second batch of tensor-namespace ops (round 2 coverage push).

Parity: `python/paddle/tensor/{math,linalg,manipulation,search,attribute,
creation}.py` — the listed functions match the reference signatures;
kernels are jnp/lax compiled by XLA (SURVEY §3.1 TPU mapping).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ._helpers import as_tensor, unary, binary, norm_axis


# ----------------------------------------------------------- elementwise


def lerp(x, y, weight, name=None):
    from ..core import dispatch
    x, y = as_tensor(x), as_tensor(y)
    if isinstance(weight, Tensor):
        # weight stays a dispatch input so it can carry gradient
        return dispatch.apply("lerp", lambda a, b, w: a + w * (b - a),
                              (x, y, weight))
    return dispatch.apply("lerp", lambda a, b: a + weight * (b - a),
                          (x, y))


def logit(x, eps=None, name=None):
    def f(a):
        if eps is not None:
            a = jnp.clip(a, eps, 1.0 - eps)
        return jnp.log(a / (1.0 - a))
    return unary("logit", f, x)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return unary("stanh", lambda a: scale_b * jnp.tanh(scale_a * a), x)


def sgn(x, name=None):
    """sign for real; x/|x| for complex."""
    def f(a):
        if jnp.issubdtype(a.dtype, jnp.complexfloating):
            mag = jnp.abs(a)
            return jnp.where(mag == 0, 0.0 + 0.0j, a / mag)
        return jnp.sign(a)
    return unary("sgn", f, x)


def gcd(x, y, name=None):
    return binary("gcd", jnp.gcd, x, y, differentiable=False)


def lcm(x, y, name=None):
    return binary("lcm", jnp.lcm, x, y, differentiable=False)


# ------------------------------------------------------------- nan-aware


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    ax = norm_axis(axis)
    return unary("nansum",
                 lambda a: jnp.nansum(a, axis=ax, keepdims=keepdim,
                                      dtype=dtype), x)


def nanmean(x, axis=None, keepdim=False, name=None):
    ax = norm_axis(axis)
    return unary("nanmean",
                 lambda a: jnp.nanmean(a, axis=ax, keepdims=keepdim), x)


def nanmedian(x, axis=None, keepdim=False, name=None):
    ax = norm_axis(axis)
    return unary("nanmedian",
                 lambda a: jnp.nanmedian(a, axis=ax, keepdims=keepdim), x)


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    ax = norm_axis(axis)
    return unary("nanquantile",
                 lambda a: jnp.nanquantile(a, q, axis=ax,
                                           keepdims=keepdim), x)


# --------------------------------------------------------------- complex


def complex(real_part, imag_part, name=None):  # noqa: A001
    return binary("complex", jax.lax.complex, real_part, imag_part)


def real(x, name=None):
    return unary("real", jnp.real, x)


def imag(x, name=None):
    return unary("imag", jnp.imag, x)


def conj(x, name=None):
    return unary("conj", jnp.conj, x)


def angle(x, name=None):
    return unary("angle", jnp.angle, x)


def as_complex(x, name=None):
    """[..., 2] float -> complex."""
    return unary("as_complex",
                 lambda a: jax.lax.complex(a[..., 0], a[..., 1]), x)


def as_real(x, name=None):
    """complex -> [..., 2] float."""
    return unary("as_real",
                 lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], -1), x)


def is_complex(x):
    return jnp.issubdtype(as_tensor(x)._data.dtype, jnp.complexfloating)


def is_floating_point(x):
    return jnp.issubdtype(as_tensor(x)._data.dtype, jnp.floating)


def is_integer(x):
    return jnp.issubdtype(as_tensor(x)._data.dtype, jnp.integer)


def is_tensor(x):
    return isinstance(x, Tensor)


def rank(x):
    return Tensor(np.asarray(as_tensor(x).ndim, np.int32))


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


# ---------------------------------------------------------------- linalg


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    from ..core import dispatch
    return dispatch.apply(
        "addmm", lambda i, a, b: beta * i + alpha * (a @ b),
        (as_tensor(input), as_tensor(x), as_tensor(y)))


def mv(x, vec, name=None):
    return binary("mv", jnp.matmul, x, vec)


def tensordot(x, y, axes=2, name=None):
    if isinstance(axes, Tensor):
        axes = axes.tolist()
    from ..core import dispatch
    return dispatch.apply(
        "tensordot", lambda a, b: jnp.tensordot(a, b, axes=axes),
        (as_tensor(x), as_tensor(y)))


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None,
        name=None):
    fw = None if fweights is None else as_tensor(fweights)._data
    aw = None if aweights is None else as_tensor(aweights)._data
    return unary("cov",
                 lambda a: jnp.cov(a, rowvar=rowvar,
                                   ddof=1 if ddof else 0,
                                   fweights=fw, aweights=aw), x)


def corrcoef(x, rowvar=True, name=None):
    return unary("corrcoef",
                 lambda a: jnp.corrcoef(a, rowvar=rowvar), x)


def eig(x, name=None):
    """General eigendecomposition (CPU-backed in jax; the reference's eig
    is CPU-only too)."""
    a = as_tensor(x)._data
    w, v = np.linalg.eig(np.asarray(a))
    return Tensor(w), Tensor(v)


def eigvals(x, name=None):
    a = as_tensor(x)._data
    return Tensor(np.linalg.eigvals(np.asarray(a)))


def cholesky_solve(x, y, upper=False, name=None):
    from ..core import dispatch

    def f(b, chol):
        return jax.scipy.linalg.cho_solve((chol, not upper), b)

    return dispatch.apply("cholesky_solve", f,
                          (as_tensor(x), as_tensor(y)))


def lstsq(x, y, rcond=None, driver=None, name=None):
    a = np.asarray(as_tensor(x)._data)
    b = np.asarray(as_tensor(y)._data)
    sol, res, rk, sv = np.linalg.lstsq(a, b, rcond=rcond)
    return (Tensor(sol), Tensor(res if res.size else np.zeros(0)),
            Tensor(np.asarray(rk)), Tensor(sv))


def lu_unpack(lu_data, lu_pivots, unpack_ludata=True, unpack_pivots=True,
              name=None):
    lu = as_tensor(lu_data)._data
    piv = np.asarray(as_tensor(lu_pivots)._data)
    m, n = lu.shape[-2], lu.shape[-1]
    k = min(m, n)
    L = jnp.tril(lu[..., :, :k], -1) + jnp.eye(m, k, dtype=lu.dtype)
    U = jnp.triu(lu[..., :k, :])
    # pivots (1-based sequential row swaps) -> permutation matrix,
    # per batch element
    batch_shape = lu.shape[:-2]
    piv2 = piv.reshape(-1, piv.shape[-1]) if batch_shape \
        else piv.reshape(1, -1)
    Ps = []
    for row in piv2:
        perm = np.arange(m)
        for i, p in enumerate(row[:k]):
            j = int(p) - 1
            perm[i], perm[j] = perm[j], perm[i]
        Ps.append(np.eye(m, dtype=np.float32)[perm].T)
    P = np.stack(Ps).reshape(tuple(batch_shape) + (m, m)) \
        if batch_shape else Ps[0]
    return Tensor(P), Tensor(L), Tensor(U)


def renorm(x, p, axis, max_norm, name=None):
    def f(a):
        dims = [d for d in range(a.ndim) if d != axis]
        norms = jnp.sum(jnp.abs(a) ** p, axis=dims, keepdims=True) \
            ** (1.0 / p)
        factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7),
                           1.0)
        return a * factor
    return unary("renorm", f, x)


def cond_number(x, p=None, name=None):
    """paddle.linalg.cond."""
    return unary("cond", lambda a: jnp.linalg.cond(a, p=p), x)


# ------------------------------------------------------------ selection


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    from ..core import dispatch

    def f(a):
        # one sort yields both: values gathered through argsort
        si = jnp.argsort(a, axis=axis)
        i = jnp.take(si, k - 1, axis=axis)
        v = jnp.take_along_axis(
            a, jnp.expand_dims(i, axis % a.ndim), axis=axis)
        v = v if keepdim else jnp.squeeze(v, axis)
        return v, (jnp.expand_dims(i, axis) if keepdim else i)

    return dispatch.apply("kthvalue", f, (as_tensor(x),))


def mode(x, axis=-1, keepdim=False, name=None):
    """Most frequent value (+ its last index) along axis — host compute,
    like the reference's CPU mode kernel."""
    a = np.asarray(as_tensor(x)._data)
    a2 = np.moveaxis(a, axis, -1)
    flat = a2.reshape(-1, a2.shape[-1])
    vals = np.empty(flat.shape[0], a.dtype)
    idxs = np.empty(flat.shape[0], np.int64)
    for i, row in enumerate(flat):
        uv, counts = np.unique(row, return_counts=True)
        m = uv[np.argmax(counts)]
        vals[i] = m
        idxs[i] = np.where(row == m)[0][-1]
    out_shape = a2.shape[:-1]
    v = vals.reshape(out_shape)
    ix = idxs.reshape(out_shape)
    if keepdim:
        v = np.expand_dims(v, axis)
        ix = np.expand_dims(ix, axis)
    return Tensor(v), Tensor(ix)


def take(x, index, mode="raise", name=None):
    from ..core import dispatch
    if mode not in ("raise", "clip", "wrap"):
        raise ValueError(f"take: unknown mode {mode!r}")

    def f(a, i):
        flat = a.reshape(-1)
        idx = i.reshape(-1)
        if mode == "raise":
            # python-style negative indexing (XLA can't raise on
            # out-of-range; clip after normalising, like the kernel)
            idx = jnp.where(idx < 0, idx + flat.shape[0], idx)
            return jnp.take(flat, idx, mode="clip").reshape(i.shape)
        return jnp.take(flat, idx, mode=mode).reshape(i.shape)

    return dispatch.apply("take", f, (as_tensor(x), as_tensor(index)))


def index_add(x, index, axis, value, name=None):
    from ..core import dispatch

    def impl(a, idx, v):
        moved = jnp.moveaxis(a, axis, 0)
        vm = jnp.moveaxis(v, axis, 0)
        out = moved.at[idx].add(vm)
        return jnp.moveaxis(out, 0, axis)

    return dispatch.apply("index_add", impl,
                          (as_tensor(x), as_tensor(index),
                           as_tensor(value)))


def multiplex(inputs, index, name=None):
    """Row-wise select among candidate tensors: out[i] =
    inputs[index[i]][i]."""
    from ..core import dispatch
    ts = [as_tensor(t) for t in inputs]

    def f(idx, *arrs):
        stacked = jnp.stack(arrs, axis=0)  # [n_cands, batch, ...]
        sel = idx.reshape(-1).astype(jnp.int32)
        batch = jnp.arange(stacked.shape[1])
        return stacked[sel, batch]

    return dispatch.apply("multiplex", f, (as_tensor(index), *ts))


# ---------------------------------------------------------- manipulation


def crop(x, shape=None, offsets=None, name=None):
    xt = as_tensor(x)
    offs = [0] * xt.ndim if offsets is None else \
        [int(o) for o in (offsets.tolist()
                          if isinstance(offsets, Tensor) else offsets)]
    if shape is None:
        shp = [-1] * xt.ndim
    else:
        shp = [int(s) for s in (shape.tolist()
                                if isinstance(shape, Tensor) else shape)]
    # -1 means "to the end": dims[i] - offsets[i] (reference semantics)
    shp = [xt.shape[i] - offs[i] if s == -1 else s
           for i, s in enumerate(shp)]

    def f(a):
        return jax.lax.dynamic_slice(a, offs, shp)

    return unary("crop", f, xt)


def diagflat(x, offset=0, name=None):
    return unary("diagflat",
                 lambda a: jnp.diagflat(a, k=offset), x)


def fill_diagonal_tensor(x, y, offset=0, dim1=0, dim2=1, name=None):
    from ..core import dispatch

    def f(a, b):
        assert a.ndim == 2 and dim1 == 0 and dim2 == 1 and offset == 0, \
            "fill_diagonal_tensor: 2-D main diagonal supported"
        n = min(a.shape[0], a.shape[1])
        idx = jnp.arange(n)
        return a.at[idx, idx].set(b[:n])

    return dispatch.apply("fill_diagonal_tensor", f,
                          (as_tensor(x), as_tensor(y)))


def unstack(x, axis=0, num=None, name=None):
    xt = as_tensor(x)
    n = num if num is not None else xt.shape[axis]
    outs = []
    for i in range(n):
        outs.append(unary(
            "unstack", lambda a, i=i: jnp.take(a, i, axis=axis), xt))
    return outs


def tril_indices(row, col=None, offset=0, dtype="int64"):
    col = row if col is None else col
    r, c = np.tril_indices(row, offset, col)
    return Tensor(np.stack([r, c]).astype(np.int64))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    col = row if col is None else col
    r, c = np.triu_indices(row, offset, col)
    return Tensor(np.stack([r, c]).astype(np.int64))


# ------------------------------------------------------------- creation


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    from ..core import dtype as dtype_mod
    dt = dtype_mod.convert_dtype(dtype) if dtype else jnp.float32
    return Tensor(jnp.logspace(float(start), float(stop), int(num),
                               base=float(base), dtype=dt))


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype=None, name=None):
    from ..core import random as rng_mod
    from ..core import dtype as dtype_mod
    dt = dtype_mod.convert_dtype(dtype) if dtype else jnp.float32
    # seed=0 draws from the global stream (paddle convention); a nonzero
    # seed must be reproducible across calls
    key = jax.random.PRNGKey(seed) if seed else rng_mod.next_key()
    return Tensor(mean + std * jax.random.normal(key, tuple(shape), dt))


# ------------------------------------------------------- tensor array


class LoDTensorArray(list):
    """create_array/array_read/array_write capability: a python list of
    Tensors (the reference's TensorArray is exactly a vector of
    LoDTensors; under jit, writes at traced indices belong in lax.scan —
    this is the eager/legacy surface)."""


def create_array(dtype="float32", initialized_list=None):
    return LoDTensorArray(initialized_list or [])


def array_write(x, i, array=None):
    i = int(i) if not isinstance(i, Tensor) else int(i.numpy())
    if array is None:
        array = LoDTensorArray()
    while len(array) <= i:
        array.append(None)
    array[i] = as_tensor(x)
    return array


def array_read(array, i):
    i = int(i) if not isinstance(i, Tensor) else int(i.numpy())
    return array[i]


def array_length(array):
    return Tensor(np.asarray(len(array), np.int64))
