"""Linear algebra ops.

Parity: `python/paddle/tensor/linalg.py` over PHI matmul
(`paddle/phi/kernels/impl/matmul_kernel_impl.h:489` → cuBLAS) and
`paddle/phi/kernels/funcs/blas/`. On TPU, matmul lowers to MXU dot_general;
AMP (`paddle/fluid/imperative/amp_auto_cast.cc` white list) is applied here
at the op boundary with bfloat16.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dispatch
from ..core.tensor import Tensor
from ._helpers import as_tensor, unary, binary


def _amp_cast2(x, y):
    """AMP casts for matmul-class ops (white list in
    `imperative/amp_auto_cast.cc`):
    - O1 auto_cast: fp32 inputs -> the amp dtype (bf16)
    - O2 decorate: weights already low-precision; harmonize a fp32 input
      to the weight dtype so decorated layers accept fp32 pipelines."""
    from ..amp.auto_cast import _amp_enabled, _amp_level, _amp_dtype
    if _amp_enabled() and _amp_level() == "O1":
        dt = _amp_dtype()
        if x.dtype == jnp.float32:
            x = x.astype(dt)
        if y.dtype == jnp.float32:
            y = y.astype(dt)
    if x.dtype != y.dtype and jnp.issubdtype(x.dtype, jnp.floating) \
            and jnp.issubdtype(y.dtype, jnp.floating):
        # cast toward the lower-precision side (the decorated weight)
        if jnp.finfo(x.dtype).bits > jnp.finfo(y.dtype).bits:
            x = x.astype(y.dtype)
        else:
            y = y.astype(x.dtype)
    return x, y


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    x, y = as_tensor(x), as_tensor(y)
    x, y = _amp_cast2(x, y)

    def _fn(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)
    return dispatch.apply("matmul", _fn, (x, y))


def mm(x, y, name=None):
    return matmul(x, y)


def bmm(x, y, name=None):
    return matmul(x, y)


def dot(x, y, name=None):
    x, y = as_tensor(x), as_tensor(y)

    def _fn(a, b):
        return jnp.sum(a * b, axis=-1)
    return dispatch.apply("dot", _fn, (x, y))


def t(x, name=None):
    x = as_tensor(x)
    if x.ndim > 2:
        raise ValueError("paddle.t only supports ndim <= 2")
    return unary("t", lambda a: a.T, x)


def matmul_fp32(x, y, transpose_x=False, transpose_y=False):
    """Non-AMP matmul used internally (e.g. loss heads)."""
    x, y = as_tensor(x), as_tensor(y)

    def _fn(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b)
    return dispatch.apply("matmul", _fn, (x, y))


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    x = as_tensor(x)
    if axis is None and p in ("fro", 2, 2.0):
        return unary("norm", lambda a: jnp.sqrt(jnp.sum(a * a)), x)
    if p == "fro":
        p = 2

    def _fn(a):
        if p == np.inf:
            return jnp.max(jnp.abs(a), axis=axis, keepdims=keepdim)
        if p == -np.inf:
            return jnp.min(jnp.abs(a), axis=axis, keepdims=keepdim)
        if p == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=axis,
                           keepdims=keepdim)
        return jnp.sum(jnp.abs(a) ** p, axis=axis,
                       keepdims=keepdim) ** (1.0 / p)
    return unary("p_norm", _fn, x)


def dist(x, y, p=2, name=None):
    return norm(binary("sub", jnp.subtract, x, y), p=float(p))


def einsum(equation, *operands):
    ts = [as_tensor(o) for o in operands]
    return dispatch.apply(
        "einsum", lambda *arrs: jnp.einsum(equation, *arrs), tuple(ts))


def transpose_last2(a):
    return jnp.swapaxes(a, -1, -2)


def cholesky(x, upper=False, name=None):
    def _fn(a):
        L = jnp.linalg.cholesky(a)
        return transpose_last2(L) if upper else L
    return unary("cholesky", _fn, as_tensor(x))


def inverse(x, name=None):
    return unary("inverse", jnp.linalg.inv, as_tensor(x))


inv = inverse


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return unary("pinv",
                 lambda a: jnp.linalg.pinv(a, rcond=rcond,
                                           hermitian=hermitian),
                 as_tensor(x))


def det(x, name=None):
    return unary("det", jnp.linalg.det, as_tensor(x))


def slogdet(x, name=None):
    x = as_tensor(x)

    def _fn(a):
        sign, logdet = jnp.linalg.slogdet(a)
        return jnp.stack([sign, logdet])
    return unary("slogdet", _fn, x)


def svd(x, full_matrices=False, name=None):
    x = as_tensor(x)

    def _fn(a):
        return jnp.linalg.svd(a, full_matrices=full_matrices)
    return dispatch.apply("svd", _fn, (x,))


def qr(x, mode="reduced", name=None):
    x = as_tensor(x)

    def _fn(a):
        return jnp.linalg.qr(a, mode=mode)
    return dispatch.apply("qr", _fn, (x,))


def eigh(x, UPLO="L", name=None):
    x = as_tensor(x)

    def _fn(a):
        w, v = jnp.linalg.eigh(a, UPLO=UPLO)
        return w, v
    return dispatch.apply("eigh", _fn, (x,))


def eigvalsh(x, UPLO="L", name=None):
    return unary("eigvalsh",
                 lambda a: jnp.linalg.eigvalsh(a, UPLO=UPLO), as_tensor(x))


def solve(x, y, name=None):
    x, y = as_tensor(x), as_tensor(y)
    return dispatch.apply("solve", jnp.linalg.solve, (x, y))


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    x, y = as_tensor(x), as_tensor(y)

    def _fn(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)
    return dispatch.apply("triangular_solve", _fn, (x, y))


def matrix_power(x, n, name=None):
    return unary("matrix_power",
                 lambda a: jnp.linalg.matrix_power(a, n), as_tensor(x))


def matrix_rank(x, tol=None, hermitian=False, name=None):
    x = as_tensor(x)
    return Tensor(jnp.linalg.matrix_rank(x._data, tol=tol))


def cross(x, y, axis=9, name=None):
    x, y = as_tensor(x), as_tensor(y)
    ax = axis if axis != 9 else -1

    def _fn(a, b):
        return jnp.cross(a, b, axis=ax)
    return dispatch.apply("cross", _fn, (x, y))


def lu(x, pivot=True, get_infos=False, name=None):
    x = as_tensor(x)
    lu_, piv = jax.scipy.linalg.lu_factor(x._data)
    if get_infos:
        return (Tensor(lu_), Tensor(piv.astype(jnp.int32)),
                Tensor(jnp.zeros((), jnp.int32)))
    return Tensor(lu_), Tensor(piv.astype(jnp.int32))


def multi_dot(tensors, name=None):
    ts = [as_tensor(t) for t in tensors]
    return dispatch.apply(
        "multi_dot", lambda *arrs: jnp.linalg.multi_dot(arrs), tuple(ts))


# round-2 additions living in extras2 but belonging to paddle.linalg
from .extras2 import (  # noqa: F401,E402
    cholesky_solve, corrcoef, cov, eig, eigvals, lstsq, lu_unpack,
    cond_number as cond,
)
