"""Random sampling ops over the stateful RNG facade.

Parity: `python/paddle/tensor/random.py` over PHI distribution kernels
(`paddle/phi/kernels/funcs/distribution_helper.h`, `gaussian_kernel.h`,
`uniform_kernel.h`), with the reference's global `Generator`
(`paddle/phi/core/generator.h`) replaced by split jax PRNG keys
(core/random.py) so the same code works eagerly and under jit tracing.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtype_mod
from ..core import random as rng
from ..core.tensor import Tensor
from ._helpers import as_tensor
from .creation import _shape_list


def _dt(dtype):
    return dtype_mod.convert_dtype(dtype) or dtype_mod.get_default_dtype()


def rand(shape, dtype=None):
    return uniform(shape, dtype, min=0.0, max=1.0)


def randn(shape, dtype=None):
    return standard_normal(shape, dtype)


def standard_normal(shape, dtype=None):
    return Tensor(jax.random.normal(rng.next_key(), _shape_list(shape),
                                    _dt(dtype)))


def normal(mean=0.0, std=1.0, shape=None, dtype=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = as_tensor(mean)._data if isinstance(mean, Tensor) else mean
        s = as_tensor(std)._data if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(
            np.shape(m) if not hasattr(m, "shape") else m.shape,
            np.shape(s) if not hasattr(s, "shape") else s.shape)
        return Tensor(jax.random.normal(rng.next_key(), shp) * s + m)
    shp = _shape_list(shape if shape is not None else [1])
    return Tensor(jax.random.normal(rng.next_key(), shp, _dt(dtype))
                  * std + mean)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0):
    key = jax.random.PRNGKey(seed) if seed else rng.next_key()
    return Tensor(jax.random.uniform(key, _shape_list(shape), _dt(dtype),
                                     minval=min, maxval=max))


def randint(low=0, high=None, shape=(1,), dtype="int64"):
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(
        rng.next_key(), _shape_list(shape), low, high,
        dtype=dtype_mod.convert_dtype(dtype)))


def randint_like(x, low=0, high=None, dtype=None):
    x = as_tensor(x)
    return randint(low, high, x.shape, dtype or x.dtype)


def randperm(n, dtype="int64"):
    return Tensor(jax.random.permutation(rng.next_key(), int(n)).astype(
        dtype_mod.convert_dtype(dtype)))


def shuffle(x, axis=0):
    x = as_tensor(x)
    return Tensor(jax.random.permutation(rng.next_key(), x._data, axis=axis,
                                         independent=False))


def bernoulli(x, name=None):
    x = as_tensor(x)
    return Tensor(
        jax.random.bernoulli(rng.next_key(), x._data).astype(x.dtype))


def poisson(x, name=None):
    x = as_tensor(x)
    return Tensor(jax.random.poisson(rng.next_key(), x._data).astype(x.dtype))


def multinomial(x, num_samples=1, replacement=False, name=None):
    x = as_tensor(x)
    probs = x._data / jnp.sum(x._data, axis=-1, keepdims=True)
    logits = jnp.log(jnp.maximum(probs, 1e-30))
    if replacement:
        out = jax.random.categorical(
            rng.next_key(), logits, shape=(*logits.shape[:-1], num_samples)
            if logits.ndim > 1 else (num_samples,), axis=-1)
    else:
        # Gumbel top-k trick for sampling without replacement
        g = jax.random.gumbel(rng.next_key(), logits.shape)
        _, out = jax.lax.top_k(logits + g, num_samples)
    return Tensor(out.astype(dtype_mod.convert_dtype("int64")))


def exponential_(x, lam=1.0, name=None):
    x = as_tensor(x)
    x._data = jax.random.exponential(rng.next_key(), x._data.shape,
                                     x._data.dtype) / lam
    return x
