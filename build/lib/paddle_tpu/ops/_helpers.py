"""Shared helpers for the op library."""
from __future__ import annotations

import numbers

import jax.numpy as jnp
import numpy as np
import jax

from ..core import dispatch
from ..core.tensor import Tensor


def as_tensor(x, dtype=None):
    if isinstance(x, Tensor):
        return x if dtype is None else x.astype(dtype)
    return Tensor(x, dtype=dtype)


def is_scalar(x) -> bool:
    return isinstance(x, numbers.Number) or (
        isinstance(x, np.ndarray) and x.ndim == 0
    )


def unary(name, fn, x, differentiable=True):
    x = as_tensor(x)
    return dispatch.apply(name, fn, (x,), differentiable=differentiable)


def binary(name, jfn, x, y, differentiable=True):
    """Elementwise binary with paddle-style scalar handling: python scalars
    are closed over (no tape node, no device transfer)."""
    if isinstance(x, Tensor) and is_scalar(y):
        return dispatch.apply(name, lambda a: jfn(a, y), (x,),
                              differentiable=differentiable)
    if is_scalar(x) and isinstance(y, Tensor):
        return dispatch.apply(name, lambda b: jfn(x, b), (y,),
                              differentiable=differentiable)
    x, y = as_tensor(x), as_tensor(y)
    return dispatch.apply(name, jfn, (x, y), differentiable=differentiable)


def norm_axis(axis):
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return axis if axis is None else int(axis)
