"""Comparison / logical / bitwise ops.

Parity: `python/paddle/tensor/logic.py` over PHI compare kernels
(`paddle/phi/kernels/compare_kernel.h`, `logical_kernel.h`).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ._helpers import as_tensor, binary
from ..core.tensor import Tensor


def _cmp(name, jfn):
    def op(x, y, name=None, _n=name, _f=jfn):
        return binary(_n, _f, x, y, differentiable=False)
    op.__name__ = name
    return op


equal = _cmp("equal", jnp.equal)
not_equal = _cmp("not_equal", jnp.not_equal)
less_than = _cmp("less_than", jnp.less)
less_equal = _cmp("less_equal", jnp.less_equal)
greater_than = _cmp("greater_than", jnp.greater)
greater_equal = _cmp("greater_equal", jnp.greater_equal)
logical_and = _cmp("logical_and", jnp.logical_and)
logical_or = _cmp("logical_or", jnp.logical_or)
logical_xor = _cmp("logical_xor", jnp.logical_xor)
bitwise_and = _cmp("bitwise_and", lambda a, b: a & b)
bitwise_or = _cmp("bitwise_or", lambda a, b: a | b)
bitwise_xor = _cmp("bitwise_xor", lambda a, b: a ^ b)


def logical_not(x, name=None):
    from ._helpers import unary
    return unary("logical_not", jnp.logical_not, as_tensor(x),
                 differentiable=False)


def bitwise_not(x, name=None):
    from ._helpers import unary
    return unary("bitwise_not", jnp.invert, as_tensor(x),
                 differentiable=False)


def equal_all(x, y, name=None):
    x, y = as_tensor(x), as_tensor(y)
    return Tensor(jnp.array_equal(x._data, y._data))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    x, y = as_tensor(x), as_tensor(y)
    return Tensor(jnp.allclose(x._data, y._data, rtol=rtol, atol=atol,
                               equal_nan=equal_nan))


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return binary("isclose",
                  lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol,
                                           equal_nan=equal_nan),
                  x, y, differentiable=False)


def is_empty(x, name=None):
    return Tensor(as_tensor(x).size == 0)
