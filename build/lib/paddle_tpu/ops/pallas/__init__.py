"""Pallas TPU kernels — the hand-written device kernels for ops where XLA
fusion isn't enough (the reference's CUDA `paddle/phi/kernels/fusion/` +
external flashattn equivalents)."""
