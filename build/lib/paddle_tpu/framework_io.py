"""paddle.save / paddle.load — checkpoint I/O.

Parity: `python/paddle/framework/io.py:646,876` (pickle-based state_dict of
params + optimizer accumulators, >4GB protocol). Tensors are stored as
numpy arrays; `paddle_tpu.distributed.checkpoint` layers orbax-style async
sharded checkpointing on top for the distributed case (SURVEY.md §5.4).
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from .core.tensor import Tensor


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return np.asarray(obj.numpy())
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_saveable(v) for v in obj)
    try:
        import jax
        if isinstance(obj, jax.Array):
            return np.asarray(obj)
    except Exception:
        pass
    return obj


def save(obj, path, protocol=4, **configs):
    dirname = os.path.dirname(path)
    if dirname:
        os.makedirs(dirname, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_saveable(obj), f, protocol=protocol)


def load(path, **configs):
    with open(path, "rb") as f:
        return pickle.load(f)
