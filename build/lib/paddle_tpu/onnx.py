"""paddle.onnx shim — export goes through StableHLO instead.

The reference exports via paddle2onnx (`python/paddle/onnx/export.py`).
The TPU-native serving artifact is the StableHLO module written by
`paddle_tpu.jit.save(layer, path, input_spec=...)`; ONNX conversion from
StableHLO is an ecosystem tool concern, not a framework one.
"""


def export(layer, path, input_spec=None, opset_version=9, **configs):
    import os
    import pickle

    from . import jit
    jit.save(layer, path, input_spec=input_spec)
    artifact = path + ".stablehlo"
    if not os.path.exists(artifact):
        with open(path + ".pdmodel", "rb") as f:
            meta = pickle.load(f)
        raise RuntimeError(
            "StableHLO export failed: "
            f"{meta.get('export_error', 'no input_spec given')}")
    return artifact
