"""fleet data_generator — the user-parser API feeding the native Dataset.

Parity: `python/paddle/distributed/fleet/data_generator/
data_generator.py` (DataGenerator / MultiSlotDataGenerator /
MultiSlotStringDataGenerator). Users subclass and implement
`generate_sample(line)` (returning a no-arg iterator of parsed samples,
each `[(slot_name, [values...]), ...]`), optionally `generate_batch`;
`run_from_stdin` keeps the reference's pipe-into-Dataset deployment
mode, and `InMemoryDataset.load_from_generator(gen, files)` (table.py)
is the in-process bridge that parses files straight into the native C++
record pool.

TPU-native line format: the native DataFeed (ps/csrc/ps_core.cpp)
parses `<label> <slot_id>:<feature_sign> ...`. A sample's `label` slot
(configurable name) becomes the label column; every other slot's values
become `<slot_id>:<sign>` pairs, with slot ids taken from the
generator's slot registry (declaration order, or an explicit mapping).
The reference's `<count> <vals...>` MultiSlotDataFeed encoding is kept
available through `_gen_str_multislot` for byte-compat pipelines.
"""
from __future__ import annotations

import sys


class DataGenerator:
    def __init__(self):
        self.batch_size_ = 32
        self._label_slot = "label"
        self._slot_ids = {}          # name -> int id (declaration order)
        self._proto_info = None

    # -- user configuration -------------------------------------------
    def set_batch(self, batch_size):
        self.batch_size_ = batch_size

    def set_label_slot(self, name):
        self._label_slot = name

    def set_slots(self, slots):
        """Explicit slot-name -> integer-id mapping (list of names ->
        ids 1..N, or a dict). Once set, unknown slot names in parsed
        samples RAISE instead of being silently auto-registered (a typo
        would otherwise train on all-zero keys)."""
        if isinstance(slots, dict):
            self._slot_ids = {str(k): int(v) for k, v in slots.items()}
        else:
            self._slot_ids = {str(n): i + 1 for i, n in enumerate(slots)}
        self._slots_frozen = True

    # -- user hooks ----------------------------------------------------
    def generate_sample(self, line):
        """Must return a NO-ARG iterator over parsed samples for this
        input line (reference contract)."""
        raise NotImplementedError(
            "implement generate_sample(line) in your DataGenerator")

    def generate_batch(self, samples):
        def local_iter():
            for s in samples:
                yield s
        return local_iter

    # -- encoding ------------------------------------------------------
    def _slot_id(self, name):
        if name not in self._slot_ids:
            if getattr(self, "_slots_frozen", False):
                raise KeyError(
                    f"slot '{name}' is not in the registry set by "
                    f"set_slots() ({sorted(self._slot_ids)}); "
                    "a mistyped slot name would otherwise emit keys "
                    "the Dataset's slot filter drops")
            self._slot_ids[name] = len(self._slot_ids) + 1
        return self._slot_ids[name]

    def _gen_str(self, parsed):
        """One parsed sample -> one native DataFeed line."""
        if not isinstance(parsed, (list, tuple)):
            raise ValueError(
                "generate_sample must yield [(name, [values...]), ...], "
                f"got {type(parsed).__name__}")
        label = 0.0
        pairs = []
        for name, values in parsed:
            if not isinstance(values, (list, tuple)) or not values:
                raise ValueError(
                    f"slot '{name}' values must be a non-empty list")
            if name == self._label_slot:
                label = float(values[0])
                continue
            sid = self._slot_id(name)
            pairs.extend(f"{sid}:{int(v)}" for v in values)
        lab = int(label) if float(label).is_integer() else label
        return f"{lab} " + " ".join(pairs) + "\n"

    def _gen_str_multislot(self, parsed):
        """Reference MultiSlotDataFeed encoding: `cnt v1 v2 ...` per
        slot (kept for byte-compat pipe deployments)."""
        out = []
        for name, values in parsed:
            out.append(str(len(values)))
            out.extend(str(v) for v in values)
        return " ".join(out) + "\n"

    # -- drivers -------------------------------------------------------
    def _emit(self, samples, write):
        batch_iter = self.generate_batch(samples)
        for sample in batch_iter():
            write(self._gen_str(sample))

    def run_from_iterable(self, lines, write=None):
        write = write or sys.stdout.write
        batch = []
        for line in lines:
            it = self.generate_sample(line)
            for parsed in it():
                if parsed is None:
                    continue
                batch.append(parsed)
                if len(batch) == self.batch_size_:
                    self._emit(batch, write)
                    batch = []
        if batch:
            self._emit(batch, write)

    def run_from_stdin(self):
        self.run_from_iterable(sys.stdin)

    def run_from_memory(self):
        self.run_from_iterable([None])


class MultiSlotDataGenerator(DataGenerator):
    """Integer feature signs (the native table keyspace)."""


class MultiSlotStringDataGenerator(DataGenerator):
    """String slots: signs hashed to uint64, namespaced per slot (the
    reference emits raw strings for the C++ feed to hash; our native
    feed takes ints, so the stable 64-bit hash happens here)."""

    def _gen_str(self, parsed):
        import hashlib
        conv = []
        for name, values in parsed:
            if name == self._label_slot:
                conv.append((name, values))
                continue
            conv.append((name, [
                int.from_bytes(
                    hashlib.blake2b(f"{name}\x00{v}".encode(),
                                    digest_size=8).digest(), "little")
                for v in values]))
        return super()._gen_str(conv)
