"""Pass-based device embedding cache.

Parity: `PSGPUWrapper` / HeterPS (`paddle/fluid/framework/fleet/
ps_gpu_wrapper.h:191 BuildGPUTask`, `:157 PullSparse`, `:195
BeginPass/EndPass`; `heter_ps/heter_comm.h`): instead of per-batch host
pull/push, a PASS (a slice of the dataset) is scanned for its unique keys,
their embeddings are bulk-pulled ONCE into a dense on-device matrix, every
batch in the pass looks embeddings up on-device (XLA gather inside the
compiled step — grads flow into the dense matrix like any parameter), and
EndPass pushes the accumulated deltas back to the host/remote table.

The reference's multi-GPU hashtable + NVLink routing collapses to one
dense [n_unique, dim] device array (sharded over the mesh when large);
the in-table SGD rule applies at EndPass via table.push of the delta.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor, Parameter
from ..nn.layer_base import Layer


class PassCache:
    """BeginPass/EndPass lifecycle around a dense device cache."""

    def __init__(self, table, dim):
        self.table = table
        self.dim = dim
        self._key_to_slot = None
        self._keys = None
        self._embedding = None  # Parameter [n_unique, dim]
        self._initial = None

    # ---- lifecycle (BuildGPUTask / BeginPass parity) ----
    def begin_pass(self, keys_iterable):
        """Collect the pass's unique keys and bulk-pull once."""
        all_keys = np.concatenate(
            [np.asarray(k).reshape(-1) for k in keys_iterable]
        ).astype(np.uint64)
        uniq = np.unique(all_keys)
        values = self.table.pull(uniq)          # one bulk host/RPC pull
        self._keys = uniq
        self._key_to_slot = {int(k): i for i, k in enumerate(uniq)}
        self._embedding = Parameter(values.astype(np.float32))
        self._initial = values.copy()
        return self

    def lookup_slots(self, keys: np.ndarray) -> np.ndarray:
        """Map raw keys -> dense slot ids (host-side, cheap dict lookups;
        feed the slots to the compiled step)."""
        flat = np.asarray(keys).reshape(-1)
        slots = np.fromiter((self._key_to_slot[int(k)] for k in flat),
                            np.int32, count=flat.size)
        return slots.reshape(np.asarray(keys).shape)

    @property
    def embedding(self) -> Parameter:
        return self._embedding

    def end_pass(self, push=True):
        """Push the accumulated embedding delta back through the table's
        SGD rule (EndPass parity). The device cache trained with plain
        SGD-like updates via the optimizer; the table receives the total
        delta as a gradient with lr-neutralising naive semantics when its
        rule is 'naive' lr=1, or as a single accumulated grad otherwise."""
        if push and self._embedding is not None:
            delta = self._initial - self._embedding.numpy()
            self.table.push(self._keys, delta.astype(np.float32))
        self._embedding = None
        self._key_to_slot = None
        self._keys = None
        self._initial = None


class PassCacheEmbedding(Layer):
    """Layer facade: forward(slots) gathers from the pass's dense cache —
    fully on-device, jit/Model.fit compatible (the cache is a Parameter,
    so compiled steps donate/update it like any weight)."""

    def __init__(self, cache: PassCache):
        super().__init__()
        self.cache = cache
        self.add_parameter("weight", cache.embedding)

    def forward(self, slots):
        from ..nn import functional as F
        return F.embedding(slots, self.weight)
