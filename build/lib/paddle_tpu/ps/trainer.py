"""Multi-threaded PS training loop.

Parity: `exe.train_from_dataset` (`python/paddle/fluid/executor.py:2582` →
`DistMultiTrainer` + `HogwildWorker::TrainFiles`
(`framework/hogwild_worker.cc:223`)): N worker threads consume batches
from the native Dataset channels, pull sparse embeddings, run the model,
push gradients — Hogwild-style (lock-free on the shard-parallel native
tables). Compiled steps release the GIL during XLA execution, so threads
overlap host pull/push with device compute.
"""
from __future__ import annotations

import threading

from .table import InMemoryDataset


class HogwildTrainer:
    """train_from_dataset(dataset, step_fn, num_threads)."""

    def __init__(self, num_threads=4):
        self.num_threads = num_threads
        self.metrics_lock = threading.Lock()
        self.losses = []

    def train_from_dataset(self, dataset: InMemoryDataset, step_fn,
                           epochs=1, shuffle_seed=None):
        """step_fn(keys, labels) -> float loss. Called concurrently from
        worker threads; the PS tables underneath are shard-locked."""
        for epoch in range(epochs):
            if shuffle_seed is not None:
                dataset.global_shuffle(seed=shuffle_seed + epoch)
            else:
                dataset.rewind()
            it = iter(dataset)
            it_lock = threading.Lock()
            errors = []

            def fetch():
                with it_lock:
                    return next(it, None)

            def worker():
                while True:
                    batch = fetch()
                    if batch is None:
                        return
                    try:
                        loss = step_fn(*batch)
                        with self.metrics_lock:
                            self.losses.append(float(loss))
                    except Exception as e:  # noqa: BLE001
                        errors.append(e)
                        return

            threads = [threading.Thread(target=worker)
                       for _ in range(self.num_threads)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            if errors:
                raise errors[0]
        return self.losses
