"""PS runtime facade.

Parity: `TheOnePSRuntime` (`python/paddle/distributed/ps/the_one_ps.py:921`
— `_init_worker:1044`, `_init_server:1202`) and the brpc client/server
pair (`BrpcPsClient`/`BrpcPsServer`).

Round-1 scope: the in-process local PS (the reference's `ps_local_client.h`
capability, used by its own single-process tests and HeterPS): tables live
in this process's native engine; init_server/init_worker manage the table
registry and persistence. The multi-host RPC transport (gRPC/TCP) is the
next native milestone — the table/accessor engine below it is already the
real one.
"""
from __future__ import annotations

import os

from .table import MemorySparseTable, MemoryDenseTable


class PSRuntime:
    """Local mode by default; distributed mode when the reference's PS env
    is present (role_maker env parsing parity, `fleet/base/role_maker.py`):
      TRAINING_ROLE=PSERVER|TRAINER
      PADDLE_PSERVERS_IP_PORT_LIST=h1:p1,h2:p2
      PADDLE_PORT / POD_IP (which endpoint this server binds)
    """

    def __init__(self):
        self._tables = {}
        self._table_configs = {}
        self._running = False
        self._server = None
        self._client = None
        eps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
        self.server_endpoints = [e for e in eps.split(",") if e]
        self.role = os.environ.get("TRAINING_ROLE", "TRAINER").upper()

    @property
    def is_distributed(self):
        return bool(self.server_endpoints)

    # ---- table registry (the_one_ps table config parity) ----
    def create_sparse_table(self, table_id, dim=8, sgd_rule="adagrad",
                            learning_rate=0.05, initial_range=0.02,
                            accessor="ctr", embedx_threshold=10.0):
        """`accessor` selects the value layout family (the_one_ps
        table-config accessor_class parity): "ctr" | "ctr_double" |
        "ctr_dymf" (see table.MemorySparseTable)."""
        self._table_configs[table_id] = dict(
            kind="sparse", dim=dim, sgd_rule=sgd_rule,
            learning_rate=learning_rate, initial_range=initial_range,
            accessor=accessor, embedx_threshold=embedx_threshold)
        if self.is_distributed:
            if self.role == "TRAINER":
                from .service import RemoteSparseTable
                self.init_worker()
                self._tables.setdefault(
                    table_id,
                    RemoteSparseTable(self._client, table_id, dim,
                                      accessor=accessor))
                return self._tables[table_id]
            # PSERVER: the real table lives in the PSServer (registered at
            # init_server from the recorded config) — no local duplicate
            return None
        if table_id not in self._tables:
            self._tables[table_id] = MemorySparseTable(
                dim, sgd_rule, learning_rate, initial_range,
                accessor=accessor, embedx_threshold=embedx_threshold)
        return self._tables[table_id]

    def create_dense_table(self, table_id, size, sgd_rule="adam",
                           learning_rate=0.01):
        self._table_configs[table_id] = dict(
            kind="dense", size=size, sgd_rule=sgd_rule,
            learning_rate=learning_rate)
        if table_id not in self._tables:
            self._tables[table_id] = MemoryDenseTable(size, sgd_rule,
                                                      learning_rate)
        return self._tables[table_id]

    def get_table(self, table_id):
        return self._tables[table_id]

    # ---- lifecycle ----
    def init_server(self, *a, **k):
        self._running = True
        if not self.is_distributed:
            return
        from .service import PSServer
        port = int(os.environ.get("PADDLE_PORT", "0") or 0)
        host = os.environ.get("POD_IP", "127.0.0.1")
        self._server = PSServer(host=host, port=port)
        for tid, cfg in self._table_configs.items():
            if cfg["kind"] == "sparse":
                self._server.register_sparse_table(
                    tid, cfg["dim"], cfg["sgd_rule"], cfg["learning_rate"],
                    cfg["initial_range"], cfg.get("accessor", "ctr"),
                    cfg.get("embedx_threshold", 10.0))
            else:
                self._server.register_dense_table(
                    tid, cfg["size"], cfg["sgd_rule"], cfg["learning_rate"])

    def run_server(self):
        self._running = True
        if self._server is not None:
            self._server.run(background=False)

    def init_worker(self, *a, **k):
        if self.is_distributed and self._client is None:
            from .service import PSClient
            self._client = PSClient(self.server_endpoints)

    def stop_worker(self):
        """Finalize THIS worker only (reference fleet.stop_worker
        semantics) — other trainers keep their servers."""
        self._running = False
        if self._client is not None:
            self._client.close()
            self._client = None

    def shutdown_servers(self):
        """Explicit server shutdown (separate from worker teardown)."""
        if self._client is None and self.is_distributed:
            self.init_worker()
        if self._client is not None:
            self._client.stop_server()
            self._client.close()
            self._client = None
        if self._server is not None:
            self._server.stop()

    def save_persistables(self, dirname):
        import numpy as np
        os.makedirs(dirname, exist_ok=True)
        # on a PS server, the live tables are inside the PSServer
        tables = self._server._tables if self._server is not None \
            else self._tables
        for tid, table in tables.items():
            if isinstance(table, MemorySparseTable):
                table.save(os.path.join(dirname, f"sparse_{tid}.bin"))
            elif isinstance(table, MemoryDenseTable):
                np.save(os.path.join(dirname, f"dense_{tid}.npy"),
                        table.pull())

    def load_persistables(self, dirname):
        import numpy as np
        tables = self._server._tables if self._server is not None \
            else self._tables
        for tid, table in tables.items():
            if isinstance(table, MemorySparseTable):
                path = os.path.join(dirname, f"sparse_{tid}.bin")
                if os.path.exists(path):
                    table.load(path)
            elif isinstance(table, MemoryDenseTable):
                path = os.path.join(dirname, f"dense_{tid}.npy")
                if os.path.exists(path):
                    table.set(np.load(path))


_runtime = None


def get_ps_runtime() -> PSRuntime:
    global _runtime
    if _runtime is None:
        _runtime = PSRuntime()
    return _runtime
