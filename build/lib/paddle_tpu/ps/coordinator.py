"""FL (federated-learning) coordinator over the PS service.

Parity: `python/paddle/distributed/ps/coordinator.py` (Coordinator /
ClientSelector / FLClient over `FLCommunicator` + the brpc
`CoordinatorClient`, `paddle/fluid/distributed/ps/service/
coordinator_client.h`). TPU-native re-design: the exchange rides the PS
server's KV namespace (service.py KV_SET/KV_GET/KV_LIST) instead of a
dedicated brpc channel; infos and strategies are JSON blobs.

Flow (reference §3.5-style round):
  1. every FL client pushes its ClientInfo (device type, compute
     capacity, bandwidth) -> `fl_info/<client_id>`;
  2. the coordinator blocks until `n_clients` infos arrived, runs its
     ClientSelector to produce a per-client FLStrategy
     (JOIN / WAIT / FINISH + iteration budget), publishes
     `fl_strategy/<round>/<client_id>`;
  3. clients poll their strategy for the round and act on it.

The reference's in-tree selector is a placeholder that JOINs everyone;
`CapacityClientSelector` here implements the real capability: rank
clients by compute_capacity * bandwidth and JOIN the top fraction.
"""
from __future__ import annotations

import abc
import json
import time


class ClientInfoAttr:
    CLIENT_ID = 0
    DEVICE_TYPE = 1
    COMPUTE_CAPACITY = 2
    BANDWIDTH = 3


class FLStrategy:
    JOIN = 0
    WAIT = 1
    FINISH = 2
    _NAMES = {0: "JOIN", 1: "WAIT", 2: "FINISH"}


class ClientSelectorBase(abc.ABC):
    def __init__(self, clients_info):
        self.clients_info = clients_info  # {client_id: info dict}
        self.fl_strategy = {}

    @abc.abstractmethod
    def select(self):
        """-> {client_id: {"next_state": str, "iteration_num": int}}"""


class ClientSelector(ClientSelectorBase):
    """Reference-default behavior: every reporting client JOINs."""

    def __init__(self, clients_info, iteration_num=99):
        super().__init__(clients_info)
        self.iteration_num = iteration_num

    def select(self):
        for cid in self.clients_info:
            self.fl_strategy[cid] = {
                "next_state": "JOIN",
                "iteration_num": self.iteration_num,
            }
        return self.fl_strategy


class CapacityClientSelector(ClientSelectorBase):
    """JOIN the top `join_fraction` of clients ranked by
    compute_capacity * bandwidth; the rest WAIT this round."""

    def __init__(self, clients_info, join_fraction=0.5, iteration_num=20):
        super().__init__(clients_info)
        self.join_fraction = join_fraction
        self.iteration_num = iteration_num

    def select(self):
        ranked = sorted(
            self.clients_info.items(),
            key=lambda kv: (float(kv[1].get("compute_capacity", 0.0))
                            * float(kv[1].get("bandwidth", 0.0))),
            reverse=True)
        n_join = max(1, int(len(ranked) * self.join_fraction))
        for rank, (cid, _info) in enumerate(ranked):
            self.fl_strategy[cid] = {
                "next_state": "JOIN" if rank < n_join else "WAIT",
                "iteration_num": self.iteration_num,
            }
        return self.fl_strategy


class FLClient:
    """Trainer-side handle: report info, receive the round strategy."""

    def __init__(self, client, client_id):
        self._client = client          # ps.service.PSClient
        self.client_id = str(client_id)

    def push_fl_client_info_sync(self, device_type="cpu",
                                 compute_capacity=1.0, bandwidth=1.0,
                                 round_id=0, **extra):
        # infos are round-scoped like strategies: a new round must
        # re-gather live capacities, not reuse stale (possibly departed)
        # clients' reports
        info = {"client_id": self.client_id, "device_type": device_type,
                "compute_capacity": compute_capacity,
                "bandwidth": bandwidth, **extra}
        self._client.kv_set(f"fl_info/{round_id}/{self.client_id}",
                            json.dumps(info).encode())

    def pull_fl_strategy(self, round_id=0, timeout=60.0, poll=0.05):
        """Block until the coordinator publishes this client's strategy
        for `round_id`; returns {"next_state": ..., "iteration_num"...}."""
        key = f"fl_strategy/{round_id}/{self.client_id}"
        deadline = time.time() + timeout
        while time.time() < deadline:
            raw = self._client.kv_get(key)
            if raw is not None:
                return json.loads(raw.decode())
            time.sleep(poll)
        raise TimeoutError(f"no FL strategy for client "
                           f"{self.client_id} round {round_id}")


class Coordinator:
    """Coordinator role: gather infos, select, publish strategies."""

    def __init__(self, client, selector_cls=ClientSelector,
                 **selector_kw):
        self._client = client
        self._selector_cls = selector_cls
        self._selector_kw = selector_kw

    def query_fl_clients_info(self, n_clients, round_id=0, timeout=60.0,
                              poll=0.05):
        """Block until n_clients infos are reported FOR THIS ROUND;
        returns {client_id: info dict}."""
        prefix = f"fl_info/{round_id}/"
        deadline = time.time() + timeout
        while time.time() < deadline:
            raw = self._client.kv_list(prefix)
            if len(raw) >= n_clients:
                return {k.rsplit("/", 1)[1]: json.loads(v.decode())
                        for k, v in raw.items()}
            time.sleep(poll)
        raise TimeoutError(
            f"only {len(self._client.kv_list(prefix))} of "
            f"{n_clients} FL clients reported for round {round_id}")

    def make_fl_strategy(self, n_clients, round_id=0, timeout=60.0):
        """One coordination round: gather -> select -> publish.
        Returns the strategy map."""
        infos = self.query_fl_clients_info(n_clients, round_id=round_id,
                                           timeout=timeout)
        selector = self._selector_cls(infos, **self._selector_kw)
        strategy = selector.select()
        for cid, strat in strategy.items():
            self._client.kv_set(f"fl_strategy/{round_id}/{cid}",
                                json.dumps(strat).encode())
        return strategy
