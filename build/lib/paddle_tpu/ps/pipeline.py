"""Double-buffered pull/train/push pipeline.

Parity: the reference overlaps PS I/O with compute through the
Communicator's async send threads + `PullDenseWorker`
(`paddle/fluid/distributed/ps/service/communicator/communicator.h:235`,
`paddle/fluid/framework/pull_dense_worker.cc`). TPU-native re-design:
three pipelined stages —

  pull(t+1)  on a prefetch thread (host C++ tables / RPC),
  step(t)    on the device (dispatch is async; the XLA step releases
             the GIL),
  push(t-1)  on a drain thread (the device->host gradient fetch blocks
             THERE, off the critical path).

Steady-state throughput = max(stage) instead of sum(stages). Gradient
pushes land at most `push_depth` batches late — the same staleness
window the reference's AsyncCommunicator exposes (async SGD semantics).
"""
from __future__ import annotations

import queue
import threading


_STOP = object()


class PullPushPipeline:
    """run(batch_iter, pull_fn, step_fn, push_fn) -> n_examples.

    pull_fn(batch)            -> acts        (host: table/RPC pull)
    step_fn(batch, acts)      -> (count, push_item or None)
                                             (dispatch device work; do
                                             NOT block on results)
    push_fn(push_item)        -> None        (fetch grads + push; may
                                             block on the device)
    """

    def __init__(self, prefetch_depth=2, push_depth=4):
        self.prefetch_depth = prefetch_depth
        self.push_depth = push_depth

    def run(self, batch_iter, pull_fn, step_fn, push_fn):
        pulled = queue.Queue(maxsize=self.prefetch_depth)
        to_push = queue.Queue(maxsize=self.push_depth)
        errors = []

        stop = threading.Event()

        def put_or_stop(item):
            while not stop.is_set():
                try:
                    pulled.put(item, timeout=0.05)
                    return True
                except queue.Full:
                    continue
            return False

        def pull_worker():
            try:
                for batch in batch_iter:
                    if not put_or_stop((batch, pull_fn(batch))):
                        return
            except Exception as e:  # noqa: BLE001
                errors.append(e)
            finally:
                put_or_stop(_STOP)

        def push_worker():
            while True:
                item = to_push.get()
                if item is _STOP:
                    return
                if errors:
                    continue  # keep draining so producers never block
                try:
                    push_fn(item)
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

        pt = threading.Thread(target=pull_worker, daemon=True)
        st = threading.Thread(target=push_worker, daemon=True)
        pt.start()
        st.start()
        seen = 0
        try:
            while True:
                item = pulled.get()
                if item is _STOP:
                    break
                if errors:
                    break
                batch, acts = item
                count, push_item = step_fn(batch, acts)
                seen += count
                if push_item is not None:
                    to_push.put(push_item)
        finally:
            stop.set()
            # unblock a pull thread waiting on a full queue
            while True:
                try:
                    pulled.get_nowait()
                except queue.Empty:
                    break
            to_push.put(_STOP)
            st.join()
            pt.join()
        if errors:
            raise errors[0]
        return seen
