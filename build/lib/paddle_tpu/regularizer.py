"""paddle.regularizer parity (`python/paddle/regularizer.py`)."""


class L2Decay:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    def __repr__(self):
        return f"L2Decay({self._coeff})"


class L1Decay:
    """L1 decay; applied via grad += coeff * sign(param) in the fused step
    (not yet wired into the optimizer fast path — treated as L2 for now is
    WRONG, so it raises if used until implemented)."""

    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)
