"""paddle.autograd namespace — PyLayer (user-defined autograd ops).

Parity: `python/paddle/autograd/py_layer.py` (`PyLayer`, `PyLayerContext`)
over the eager custom-grad-node machinery (`eager/pylayer/
py_layer_node.h`). A PyLayer's backward plugs straight into the GradNode
graph; its compute can be arbitrary python over Tensors (each op still
XLA-dispatched).
"""
from __future__ import annotations

from .core import autograd as _ag
from .core.autograd import no_grad, enable_grad, grad  # noqa: F401
from .core.autograd import run_backward
from .core.dispatch import _edge_for
from .core.tensor import Tensor


def backward(tensors, grad_tensors=None, retain_graph=False):
    """paddle.autograd.backward parity."""
    run_backward(tensors, grad_tensors, retain_graph)


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        self._saved = tensors

    def saved_tensor(self):
        """Reference API: a METHOD returning the saved tuple
        (python/paddle/autograd/py_layer.py)."""
        return self._saved

    saved_tensors = saved_tensor


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grad_outputs):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        need_grad = _ag.is_grad_enabled() and any(
            not t.stop_gradient for t in tensor_inputs)
        with _ag.no_grad():
            outputs = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(outputs, (tuple, list))
        outs = list(outputs) if multi else [outputs]
        if not need_grad:
            return outputs

        cls_ref = cls

        def vjp_fn(cotangents):
            cts = cotangents if isinstance(cotangents, tuple) else \
                (cotangents,)
            g_tensors = [Tensor(c) for c in cts]
            with _ag.no_grad():
                in_grads = cls_ref.backward(ctx, *g_tensors)
            in_grads = in_grads if isinstance(in_grads, (tuple, list)) \
                else (in_grads,)
            out = []
            gi = iter(in_grads)
            for a in args:
                if isinstance(a, Tensor):
                    g = next(gi, None)
                    out.append(g._data if isinstance(g, Tensor)
                               else (g if g is not None else None))
            # autograd engine expects one cotangent per recorded input
            return tuple(o if o is not None else
                         _zero_like(t) for o, t in zip(out, tensor_inputs))

        node = _ag.GradNode(
            cls.__name__, vjp_fn,
            [_edge_for(t) for t in tensor_inputs],
            len(outs),
            [o._data.shape for o in outs],
            [o._data.dtype for o in outs])
        for i, o in enumerate(outs):
            o.stop_gradient = False
            o._grad_node = node
            o._out_slot = i
        return outputs


def _zero_like(t):
    import jax.numpy as jnp
    return jnp.zeros(t._data.shape, t._data.dtype)


class PyLayerBackwardFunction:  # parity alias
    pass
