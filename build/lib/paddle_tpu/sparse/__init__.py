"""paddle_tpu.sparse — sparse tensors.

Parity: `paddle.sparse` (`python/paddle/incubate/sparse/` in the snapshot:
SparseCooTensor/SparseCsrTensor, `paddle/phi/core/sparse_coo_tensor.h`)
over `jax.experimental.sparse` (BCOO — XLA-lowerable sparse ops).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor
from ..ops._helpers import as_tensor


class SparseTensor(Tensor):
    """Tensor holding a BCOO; densifies lazily when a dense op touches it
    (so inherited Tensor methods keep working — a dense fallback, like the
    reference's coo→dense kernel fallbacks)."""

    __slots__ = ("_bcoo", "_dense_cache")

    def __init__(self, bcoo, stop_gradient=True):
        self._bcoo = bcoo
        self._dense_cache = None
        super().__init__(jnp.zeros((), jnp.float32),
                         stop_gradient=stop_gradient)
        self._dense_cache = None  # discard the placeholder written above

    @property
    def _data(self):
        if self._dense_cache is None:
            self._dense_cache = self._bcoo.todense()
        return self._dense_cache

    @_data.setter
    def _data(self, value):
        self._dense_cache = value

    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    def to_dense(self):
        return Tensor(self._bcoo.todense())

    def values(self):
        return Tensor(self._bcoo.data)

    def indices(self):
        return Tensor(jnp.swapaxes(self._bcoo.indices, 0, 1))

    def numpy(self):
        return np.asarray(self._bcoo.todense())

    def nnz(self):
        return int(self._bcoo.nse)

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, "
                f"nnz={self.nnz()})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    """indices: [ndim, nnz] (paddle layout)."""
    idx = as_tensor(indices)._data
    vals = as_tensor(values, dtype=dtype)._data
    idx_t = jnp.swapaxes(idx, 0, 1).astype(jnp.int32)  # [nnz, ndim]
    if shape is None:
        shape = tuple(int(i) for i in (idx.max(axis=1) + 1).tolist())
    bcoo = jsparse.BCOO((vals, idx_t), shape=tuple(int(s) for s in shape))
    return SparseTensor(bcoo, stop_gradient=stop_gradient)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True):
    crows = np.asarray(as_tensor(crows).numpy())
    cols = np.asarray(as_tensor(cols).numpy())
    vals = as_tensor(values, dtype=dtype)._data
    # expand crows to row indices -> BCOO
    rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
    idx = jnp.stack([jnp.asarray(rows, jnp.int32),
                     jnp.asarray(cols, jnp.int32)], axis=1)
    bcoo = jsparse.BCOO((vals, idx), shape=tuple(int(s) for s in shape))
    return SparseTensor(bcoo, stop_gradient=stop_gradient)


def matmul(x, y):
    """sparse @ dense — BCOO dot_general, no densification."""
    if isinstance(x, SparseTensor):
        yd = as_tensor(y)._data
        return Tensor(x._bcoo @ yd)
    raise TypeError("sparse.matmul expects a SparseTensor lhs")


def mv(x, vec):
    """sparse matrix @ dense vector."""
    return matmul(x, vec)


def masked_matmul(x, y, mask):
    """dense @ dense evaluated ONLY at `mask`'s nonzero positions
    (reference sparse.masked_matmul / SDDMM): out is sparse with mask's
    pattern. Computes a gathered row·col dot per nonzero — O(nnz·k), not
    O(n·m·k)."""
    xd = as_tensor(x)._data
    yd = as_tensor(y)._data
    idx = mask._bcoo.indices  # [nnz, 2]
    rows = xd[idx[:, 0], :]          # [nnz, k]
    cols = yd[:, idx[:, 1]].T        # [nnz, k]
    vals = jnp.sum(rows * cols, axis=-1).astype(xd.dtype)
    return SparseTensor(jsparse.BCOO((vals, idx), shape=mask._bcoo.shape))


def add(x, y):
    if isinstance(x, SparseTensor) and isinstance(y, SparseTensor):
        return SparseTensor(x._bcoo + y._bcoo)
    raise TypeError("sparse.add expects SparseTensors")


def _unary_on_values(fn, x: "SparseTensor") -> "SparseTensor":
    """Value-space op: touches only the nnz values (real sparse compute,
    like the reference's sparse unary kernels
    `paddle/phi/kernels/sparse/unary_kernel.h`)."""
    b = x._bcoo
    return SparseTensor(jsparse.BCOO((fn(b.data), b.indices),
                                     shape=b.shape))


def relu(x):
    return _unary_on_values(lambda v: jnp.maximum(v, 0), x)


def sin(x):
    return _unary_on_values(jnp.sin, x)


def tanh(x):
    return _unary_on_values(jnp.tanh, x)


def sqrt(x):
    return _unary_on_values(jnp.sqrt, x)


def abs(x):  # noqa: A001 - paddle API name
    return _unary_on_values(jnp.abs, x)


def neg(x):
    return _unary_on_values(jnp.negative, x)


def pow(x, factor):  # noqa: A001 - paddle API name
    return _unary_on_values(lambda v: jnp.power(v, factor), x)


def scale(x, scale_, bias=0.0, bias_after_scale=True):
    if bias_after_scale:
        return _unary_on_values(lambda v: v * scale_ + bias, x)
    return _unary_on_values(lambda v: (v + bias) * scale_, x)


def cast(x, index_dtype=None, value_dtype=None):
    from ..core import dtype as dtype_mod
    b = x._bcoo
    vals = b.data if value_dtype is None else \
        b.data.astype(dtype_mod.convert_dtype(value_dtype))
    idx = b.indices if index_dtype is None else \
        b.indices.astype(dtype_mod.convert_dtype(index_dtype))
    return SparseTensor(jsparse.BCOO((vals, idx), shape=b.shape))


def multiply(x, y):
    """elementwise sparse*sparse (same pattern) or sparse*scalar."""
    if isinstance(y, (int, float)):
        return _unary_on_values(lambda v: v * y, x)
    if isinstance(x, SparseTensor) and isinstance(y, SparseTensor):
        return SparseTensor(jsparse.bcoo_multiply_sparse(x._bcoo,
                                                         y._bcoo))
    raise TypeError("sparse.multiply expects sparse operands or a scalar")


def transpose(x, perm):
    return SparseTensor(jsparse.bcoo_transpose(x._bcoo,
                                               permutation=tuple(perm)))


def coalesce(x):
    """Sum duplicate coordinates (reference CoalesceKernel)."""
    return SparseTensor(jsparse.bcoo_sum_duplicates(x._bcoo))


def softmax(x, axis=-1):
    """Row-wise softmax over the SPARSE pattern only (2-D COO; the
    reference's sparse softmax semantics: missing entries are -inf, i.e.
    excluded), via segment max/sum over the row index — O(nnz)."""
    b = x._bcoo
    if len(b.shape) != 2 or axis not in (-1, 1):
        raise NotImplementedError("sparse.softmax: 2-D, last axis only")
    rows = b.indices[:, 0]
    n_rows = b.shape[0]
    rmax = jax.ops.segment_max(b.data, rows, num_segments=n_rows)
    e = jnp.exp(b.data - rmax[rows])
    rsum = jax.ops.segment_sum(e, rows, num_segments=n_rows)
    return SparseTensor(jsparse.BCOO((e / rsum[rows], b.indices),
                                     shape=b.shape))


def is_sparse(x):
    return isinstance(x, SparseTensor)


class _SparseReLU:
    def __call__(self, x):
        return relu(x)


class _SparseSoftmax:
    def __init__(self, axis=-1):
        self.axis = axis

    def __call__(self, x):
        return softmax(x, self.axis)


class nn:  # namespace shim: paddle.sparse.nn.ReLU()/Softmax()
    ReLU = _SparseReLU
    Softmax = _SparseSoftmax
