"""paddle_tpu.metric — `python/paddle/metric/metrics.py` parity
(Metric, Accuracy, Precision, Recall, Auc)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from .. import ops


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred = pred if isinstance(pred, Tensor) else Tensor(pred)
        label = label if isinstance(label, Tensor) else Tensor(label)
        _, top_idx = ops.topk(pred, self.maxk, axis=-1)
        lab = label.numpy()
        if lab.ndim == top_idx.ndim:
            lab = lab.squeeze(-1)
        correct = (top_idx.numpy() == lab[..., None])
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = correct.numpy() if isinstance(correct, Tensor) else \
            np.asarray(correct)
        num_samples = c.shape[0]
        accs = []
        for i, k in enumerate(self.topk):
            num_corrects = c[..., :k].sum()
            accs.append(float(num_corrects) / max(num_samples, 1))
            self.total[i] += num_corrects
            self.count[i] += num_samples
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / c if c > 0 else 0.0
               for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = preds.numpy() if isinstance(preds, Tensor) else np.asarray(preds)
        l = labels.numpy() if isinstance(labels, Tensor) else \
            np.asarray(labels)
        pred_pos = (p > 0.5).reshape(-1).astype(np.int32)
        l = l.reshape(-1).astype(np.int32)
        self.tp += int(((pred_pos == 1) & (l == 1)).sum())
        self.fp += int(((pred_pos == 1) & (l == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = preds.numpy() if isinstance(preds, Tensor) else np.asarray(preds)
        l = labels.numpy() if isinstance(labels, Tensor) else \
            np.asarray(labels)
        pred_pos = (p > 0.5).reshape(-1).astype(np.int32)
        l = l.reshape(-1).astype(np.int32)
        self.tp += int(((pred_pos == 1) & (l == 1)).sum())
        self.fn += int(((pred_pos == 0) & (l == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """Bucketed AUC — parity with the reference's distributed AUC calculator
    (`paddle/fluid/framework/fleet/metrics.cc` global AUC buckets; buckets
    can be all-reduced across workers by fleet.metrics)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = preds.numpy() if isinstance(preds, Tensor) else np.asarray(preds)
        l = labels.numpy() if isinstance(labels, Tensor) else \
            np.asarray(labels)
        if p.ndim == 2 and p.shape[1] == 2:
            p = p[:, 1]
        p = p.reshape(-1)
        l = l.reshape(-1)
        idx = np.clip((p * self.num_thresholds).astype(np.int64), 0,
                      self.num_thresholds)
        np.add.at(self._stat_pos, idx, (l == 1))
        np.add.at(self._stat_neg, idx, (l == 0))

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, np.int64)

    def accumulate(self):
        tot_pos = 0.0
        tot_neg = 0.0
        auc = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_pos + tot_pos) * (new_neg - tot_neg) / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        denom = tot_pos * tot_neg
        return float(auc / denom) if denom else 0.0

    def name(self):
        return self._name
