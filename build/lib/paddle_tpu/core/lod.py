"""LoDTensor — variable-length sequence batches.

Parity: `paddle/fluid/framework/lod_tensor.h` (level-of-detail tensor: a
dense buffer + per-level offset table describing a ragged batch) and the
python `fluid.create_lod_tensor` / `Tensor.lod()` surface, used by the
PS/NLP legacy paths (sequence ops, DataFeed var-len slots).

TPU-native stance: XLA wants static shapes, so the ragged structure lives
as (data, offsets) pairs on host — exactly the SURVEY §7 plan — with
conversions to padded+mask form (what compiled steps consume) and
segment-id form (what segment reductions consume).
"""
from __future__ import annotations

import numpy as np

from .tensor import Tensor


class LoDTensor(Tensor):
    """Dense data + offset levels. offsets are python lists of ints
    (host metadata, never traced)."""

    __slots__ = ("_lod",)

    def __init__(self, data, lod=None, stop_gradient=True):
        d = data._data if isinstance(data, Tensor) else data
        super().__init__(d, stop_gradient=stop_gradient)
        self._lod = [list(map(int, level)) for level in (lod or [])]

    def lod(self):
        return self._lod

    def set_lod(self, lod):
        self._lod = [list(map(int, level)) for level in lod]

    def recursive_sequence_lengths(self):
        return [[b - a for a, b in zip(level, level[1:])]
                for level in self._lod]

    # ----------------------------------------------------- conversions
    def sequence_count(self):
        return len(self._lod[-1]) - 1 if self._lod else self.shape[0]

    def to_padded(self, pad_value=0.0):
        """-> (padded [n_seq, max_len, *feat], length [n_seq]) Tensors —
        the static-shape form compiled steps consume."""
        assert self._lod, "LoDTensor without lod is already dense"
        offs = self._lod[-1]
        lens = [b - a for a, b in zip(offs, offs[1:])]
        n, m = len(lens), max(lens) if lens else 0
        feat = self.shape[1:]
        arr = np.asarray(self.numpy())
        out = np.full((n, m, *feat), pad_value, arr.dtype)
        for i, (a, b) in enumerate(zip(offs, offs[1:])):
            out[i, : b - a] = arr[a:b]
        return Tensor(out), Tensor(np.asarray(lens, np.int64))

    def segment_ids(self):
        """-> int32 [total_len] mapping each row to its sequence — the
        form segment reductions (sequence_pool parity) consume."""
        assert self._lod
        offs = self._lod[-1]
        lens = [b - a for a, b in zip(offs, offs[1:])]
        return Tensor(np.repeat(np.arange(len(lens), dtype=np.int32),
                                lens))

    def __repr__(self):
        return (f"LoDTensor(shape={self.shape}, lod={self._lod})")


def create_lod_tensor(data, recursive_seq_lens, place=None):
    """fluid.create_lod_tensor parity: lengths -> offsets."""
    lod = []
    for lens in recursive_seq_lens:
        offs = [0]
        for ln in lens:
            offs.append(offs[-1] + int(ln))
        lod.append(offs)
    arr = data.numpy() if isinstance(data, Tensor) else np.asarray(data)
    return LoDTensor(arr, lod)


def from_padded(padded, lengths):
    """(padded [n, m, *feat], lengths [n]) -> LoDTensor (ragged rows
    concatenated)."""
    p = padded.numpy() if isinstance(padded, Tensor) else \
        np.asarray(padded)
    lens = [int(x) for x in np.asarray(
        lengths.numpy() if isinstance(lengths, Tensor) else lengths)]
    rows = [p[i, :ln] for i, ln in enumerate(lens)]
    offs = [0]
    for ln in lens:
        offs.append(offs[-1] + ln)
    return LoDTensor(np.concatenate(rows, axis=0) if rows
                     else p[:0, 0], [offs])


def sequence_pool(x: LoDTensor, pool_type="sum"):
    """sequence_pool op parity over segment reductions (runs on device)."""
    import jax
    seg = x.segment_ids()._data
    n = x.sequence_count()
    data = x._data
    if pool_type in ("sum", "average", "mean"):
        out = jax.ops.segment_sum(data, seg, num_segments=n)
        if pool_type in ("average", "mean"):
            lens = jax.ops.segment_sum(
                np.ones((data.shape[0],), np.float32), seg,
                num_segments=n)
            out = out / np.maximum(
                np.asarray(lens).reshape([-1] + [1] * (out.ndim - 1)), 1)
    elif pool_type == "max":
        out = jax.ops.segment_max(data, seg, num_segments=n)
    elif pool_type == "min":
        out = jax.ops.segment_min(data, seg, num_segments=n)
    else:
        raise ValueError(f"unknown pool_type {pool_type}")
    return Tensor(out)
