from . import dtype, place, random, autograd, dispatch
from .tensor import Tensor, Parameter

__all__ = ["dtype", "place", "random", "autograd", "dispatch", "Tensor",
           "Parameter"]
