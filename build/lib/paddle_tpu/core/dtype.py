"""Dtype system for paddle_tpu.

Capability parity with the reference's dtype handling
(`paddle/phi/common/data_type.h`, `python/paddle/fluid/framework.py` dtype
conversions), realised as thin aliases over numpy/jax dtypes. bfloat16 is
first-class (TPU-native), float16 is supported but discouraged on TPU.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import ml_dtypes

# Canonical dtype objects are numpy dtype instances (jnp uses the same).
bool_ = np.dtype(np.bool_)
uint8 = np.dtype(np.uint8)
uint16 = np.dtype(np.uint16)
uint32 = np.dtype(np.uint32)
uint64 = np.dtype(np.uint64)
int8 = np.dtype(np.int8)
int16 = np.dtype(np.int16)
int32 = np.dtype(np.int32)
int64 = np.dtype(np.int64)
float16 = np.dtype(np.float16)
bfloat16 = np.dtype(ml_dtypes.bfloat16)
float32 = np.dtype(np.float32)
float64 = np.dtype(np.float64)
complex64 = np.dtype(np.complex64)
complex128 = np.dtype(np.complex128)

_ALIASES = {
    "bool": bool_, "uint8": uint8, "int8": int8, "int16": int16,
    "int32": int32, "int64": int64, "float16": float16, "fp16": float16,
    "bfloat16": bfloat16, "bf16": bfloat16, "float32": float32,
    "fp32": float32, "float64": float64, "fp64": float64,
    "complex64": complex64, "complex128": complex128,
    "float": float32, "double": float64, "half": float16, "int": int32,
    "long": int64,
}

_default_dtype = float32


def _canonical(d: np.dtype) -> np.dtype:
    """TPU-native canonicalisation: without jax x64, 64-bit int/float are
    emulated or truncated — the framework stores them as 32-bit (the
    reference's int64 indices become int32, which is what XLA:TPU natively
    gathers/scatters with)."""
    import jax
    if jax.config.jax_enable_x64:
        return d
    return {np.dtype(np.int64): int32, np.dtype(np.uint64): uint32,
            np.dtype(np.float64): float32,
            np.dtype(np.complex128): complex64}.get(d, d)


def convert_dtype(dtype):
    """Normalise any dtype spec (str / np.dtype / jnp type) to np.dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, np.dtype):
        return _canonical(dtype)
    if isinstance(dtype, str):
        key = dtype.lower()
        if key in _ALIASES:
            return _canonical(_ALIASES[key])
        return _canonical(np.dtype(dtype))
    return _canonical(np.dtype(dtype))


def dtype_name(dtype) -> str:
    d = convert_dtype(dtype)
    return d.name


def set_default_dtype(d):
    """paddle.set_default_dtype parity (python/paddle/framework/framework.py)."""
    global _default_dtype
    d = convert_dtype(d)
    if d not in (float16, bfloat16, float32, float64):
        raise TypeError(f"default dtype must be floating, got {d}")
    _default_dtype = d


def get_default_dtype():
    return _default_dtype


def is_floating(dtype) -> bool:
    d = convert_dtype(dtype)
    return d in (float16, bfloat16, float32, float64)


def is_integer(dtype) -> bool:
    d = convert_dtype(dtype)
    return np.issubdtype(d, np.integer) or d == bool_


def is_complex(dtype) -> bool:
    d = convert_dtype(dtype)
    return np.issubdtype(d, np.complexfloating)


def finfo(dtype):
    return jnp.finfo(convert_dtype(dtype))


def iinfo(dtype):
    return jnp.iinfo(convert_dtype(dtype))
