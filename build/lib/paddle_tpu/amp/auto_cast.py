"""auto_cast / decorate.

O1: white-listed ops (matmul/conv/linear — the MXU ops) run in bf16; the
cast happens at op dispatch (`ops/linalg.py:_amp_cast2`), mirroring the
generated-code cast insertion in the reference
(`eager/auto_code_generator/generator/eager_gen.py:1395`,
`imperative/amp_auto_cast.cc` lists).
O2: `decorate` casts the model's float parameters to bf16 wholesale.
"""
from __future__ import annotations

import contextlib

from ..core import dtype as dtype_mod

_state = {"enabled": False, "level": "O1", "dtype": None}


def _amp_enabled():
    return _state["enabled"]


def _amp_level():
    return _state["level"]


def _amp_dtype():
    return _state["dtype"]


# the reference's white/black lists (imperative/amp_auto_cast.cc); on TPU
# only the matmul-class ops matter — everything else is bandwidth-bound and
# fuses anyway.
WHITE_LIST = {"matmul", "conv1d", "conv2d", "conv3d", "linear", "einsum",
              "bmm", "mm"}
BLACK_LIST = {"softmax", "log_softmax", "cross_entropy", "exp", "log",
              "mean", "sum", "norm", "layer_norm", "batch_norm"}


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    prev = dict(_state)
    _state["enabled"] = enable
    _state["level"] = level
    _state["dtype"] = dtype_mod.convert_dtype(dtype)
    try:
        yield
    finally:
        _state.update(prev)


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """O2: cast model params to low precision (master weights live in the
    optimizer's fp32 accumulators — `multi_precision` capability)."""
    dt = dtype_mod.convert_dtype(dtype)
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        for m in model_list:
            m._cast_all(dt)
    if optimizers is None:
        return models if single else model_list
    return (models if single else model_list), optimizers


amp_decorate = decorate
