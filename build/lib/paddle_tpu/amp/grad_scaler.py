"""GradScaler — dynamic loss scaling.

Parity: `python/paddle/amp/grad_scaler.py` →
`python/paddle/fluid/dygraph/amp/loss_scaler.py:293` (`AmpScaler`), built on
the `check_finite_and_unscale` / `update_loss_scaling` kernels
(`paddle/fluid/operators/amp/`). With bf16 (TPU default) scaling is not
needed; the class honours `enable=False` transparently and implements the
full dynamic-scale state machine for fp16 parity.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from .. import ops


class OptimizerState:
    INIT = 0
    UNSCALED = 1
    STEPPED = 2


class GradScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        # per-optimizer (state, found_inf) machine, mirroring reference
        # python/paddle/amp/grad_scaler.py:199 — a user's explicit
        # unscale_() (grad-clip pattern) must not be repeated inside
        # step(), and step() twice per update() is an error. found_inf is
        # kept per-optimizer too: a later unscale_() of a second optimizer
        # (e.g. GAN D/G) must not mask the first one's inf.
        self._opt_states = {}

    def _state(self, optimizer):
        return self._opt_states.get(
            id(optimizer), (OptimizerState.INIT, False))[0]

    def scale(self, var):
        if not self._enable:
            return var
        return ops.scale(var, self._scale)

    def unscale_(self, optimizer):
        if not self._enable:
            return
        state = self._state(optimizer)
        if state == OptimizerState.UNSCALED:
            raise RuntimeError(
                "unscale_() has already been called on this optimizer "
                "since the last update().")
        if state == OptimizerState.STEPPED:
            raise RuntimeError("unscale_() is being called after step().")
        params = optimizer._params_with_grad()
        found_inf = False
        inv = 1.0 / self._scale
        for p in params:
            g = p.grad._data.astype(jnp.float32) * inv
            if not bool(jnp.isfinite(g).all()):
                found_inf = True
            p.grad._data = g.astype(p.grad._data.dtype)
        self._found_inf = self._found_inf or found_inf
        self._opt_states[id(optimizer)] = (OptimizerState.UNSCALED,
                                           found_inf)

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        self.update()

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        state = self._state(optimizer)
        if state == OptimizerState.STEPPED:
            raise RuntimeError(
                "step() has already been called since the last update().")
        if state == OptimizerState.INIT:
            self.unscale_(optimizer)
        found_inf = self._opt_states[id(optimizer)][1]
        if not found_inf:
            optimizer.step()
        self._opt_states[id(optimizer)] = (OptimizerState.STEPPED,
                                           found_inf)

    def update(self):
        self._opt_states.clear()
        found_inf = self._found_inf
        self._found_inf = False  # next backward cycle starts clean
        if not self._enable or not self._dynamic:
            return
        if found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        return Tensor(np.float32(self._scale))

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "good_steps": self._good_steps,
                "bad_steps": self._bad_steps}

    def load_state_dict(self, d):
        self._scale = d.get("scale", self._scale)
        self._good_steps = d.get("good_steps", 0)
        self._bad_steps = d.get("bad_steps", 0)


AmpScaler = GradScaler
