"""paddle_tpu.amp — automatic mixed precision, bf16-first.

Parity: `python/paddle/amp/auto_cast.py` (O1 list-driven cast at op
dispatch, O2 pure low-precision `decorate`) and `grad_scaler.py`
(`check_finite_and_unscale` + `update_loss_scaling` ops,
`python/paddle/fluid/dygraph/amp/loss_scaler.py:293`).

TPU-native: the default low dtype is bfloat16 — same exponent range as
fp32, so dynamic loss scaling is unnecessary (GradScaler keeps the API and
becomes a near-no-op unless fp16 is forced).
"""
from .auto_cast import auto_cast, decorate, amp_guard, amp_decorate  # noqa
from .grad_scaler import GradScaler, AmpScaler  # noqa
