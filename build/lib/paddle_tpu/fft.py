"""paddle_tpu.fft — `python/paddle/fft.py` parity over jnp.fft (XLA FFT)."""
from __future__ import annotations

import jax.numpy as jnp

from .ops._helpers import as_tensor, unary


def _fft_op(name, jfn):
    def op(x, n=None, axis=-1, norm="backward", name_=None, _f=jfn,
           _n=name):
        x = as_tensor(x)
        return unary(_n, lambda a: _f(a, n=n, axis=axis, norm=norm), x)
    op.__name__ = name
    return op


fft = _fft_op("fft", jnp.fft.fft)
ifft = _fft_op("ifft", jnp.fft.ifft)
rfft = _fft_op("rfft", jnp.fft.rfft)
irfft = _fft_op("irfft", jnp.fft.irfft)
hfft = _fft_op("hfft", jnp.fft.hfft)
ihfft = _fft_op("ihfft", jnp.fft.ihfft)


def _fftn_op(name, jfn):
    def op(x, s=None, axes=None, norm="backward", name_=None, _f=jfn,
           _n=name):
        x = as_tensor(x)
        return unary(_n, lambda a: _f(a, s=s, axes=axes, norm=norm), x)
    op.__name__ = name
    return op


fft2 = _fftn_op("fft2", jnp.fft.fft2)
ifft2 = _fftn_op("ifft2", jnp.fft.ifft2)
fftn = _fftn_op("fftn", jnp.fft.fftn)
ifftn = _fftn_op("ifftn", jnp.fft.ifftn)
rfft2 = _fftn_op("rfft2", jnp.fft.rfft2)
irfft2 = _fftn_op("irfft2", jnp.fft.irfft2)
rfftn = _fftn_op("rfftn", jnp.fft.rfftn)
irfftn = _fftn_op("irfftn", jnp.fft.irfftn)


def fftfreq(n, d=1.0, dtype=None):
    from .core.tensor import Tensor
    return Tensor(jnp.fft.fftfreq(n, d))


def rfftfreq(n, d=1.0, dtype=None):
    from .core.tensor import Tensor
    return Tensor(jnp.fft.rfftfreq(n, d))


def fftshift(x, axes=None):
    return unary("fftshift", lambda a: jnp.fft.fftshift(a, axes),
                 as_tensor(x))


def ifftshift(x, axes=None):
    return unary("ifftshift", lambda a: jnp.fft.ifftshift(a, axes),
                 as_tensor(x))
