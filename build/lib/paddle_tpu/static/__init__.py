"""paddle.static shim.

The reference's static Program/Executor stack (SURVEY.md §2.2) is subsumed
by whole-step jax.jit (see jit/). This module keeps the few static symbols
user code touches: InputSpec, and save/load_inference_model mapped onto
jit.save/load (StableHLO export = the inference Program).
"""
from ..hapi.model import InputSpec  # noqa: F401
from .. import jit as _jit


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         **kwargs):
    raise NotImplementedError(
        "static graphs are not part of the TPU-native design; use "
        "paddle_tpu.jit.save(layer, path, input_spec=[...]) which exports "
        "an AOT StableHLO module (AnalysisPredictor capability)")


def load_inference_model(path_prefix, executor=None, **kwargs):
    return _jit.load(path_prefix)


class Executor:
    def __init__(self, place=None):
        raise NotImplementedError(
            "the static Executor is replaced by compiled eager execution "
            "(SURVEY.md §7.5); use paddle_tpu.jit.to_static or Model.fit")
