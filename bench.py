"""Benchmark: GPT-2 350M-class causal-LM training throughput on one chip.

Metric of record (BASELINE.md): GPT tokens/sec/chip for the compiled
train step (forward + backward + fused Adam in one XLA executable,
bf16 compute / fp32 master params, remat on).

vs_baseline derivation: the reference's target is "V100x8-class
throughput" (BASELINE.json). Published Megatron-LM-era numbers put a
345M-parameter GPT-2 at ~9-10k tokens/sec on one V100 with fp16; we use
10_000 tokens/sec/chip as the per-chip baseline, so vs_baseline =
tokens_per_sec / 10_000 (1.0 = V100 parity; >1 beats it).
"""
import json
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.parallel.hybrid_gpt import GPTConfig, HybridGPT

    dev = jax.devices()[0]
    on_tpu = dev.platform in ("tpu", "axon")

    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, seq_len=1024, d_model=1024,
                        n_heads=16, n_layers=24, dp=1, pp=1, mp=1,
                        micro_batches=1, remat=True, zero_stage=0,
                        compute_dtype=jnp.bfloat16)
        # 16 and 32 measure within noise of each other with fused
        # attention (~17.5-18.4k tokens/s); 64 fails to compile (OOM)
        batch = 32
        iters = 12
    else:  # CPU smoke mode
        cfg = GPTConfig(vocab_size=1024, seq_len=128, d_model=128,
                        n_heads=4, n_layers=2, dp=1, pp=1, mp=1,
                        micro_batches=1, remat=False, zero_stage=0,
                        compute_dtype=jnp.float32)
        batch = 4
        iters = 3

    trainer = HybridGPT(cfg, devices=[dev])
    params, opt = trainer.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    tok = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, cfg.seq_len)),
                      jnp.int32)
    lab = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, cfg.seq_len)),
                      jnp.int32)

    # warmup / compile (device_get, not block_until_ready — the latter can
    # return early through the axon relay)
    params, opt, loss = trainer.train_step(params, opt, tok, lab,
                                           step_num=1)
    float(jax.device_get(loss))

    # Timing barrier: on the axon relay, block_until_ready can return
    # early (bogus timings), but jax.device_get fetches real bytes and the
    # final step's loss data-depends on every previous step — one fetch at
    # the end is an honest barrier without the ~0.3s/step host round-trip
    # a per-step fetch would add.
    t0 = time.perf_counter()
    for i in range(iters):
        params, opt, loss = trainer.train_step(params, opt, tok, lab,
                                               step_num=i + 2)
    final_loss = float(jax.device_get(loss))
    dt = time.perf_counter() - t0
    assert np.isfinite(final_loss)

    tokens_per_sec = batch * cfg.seq_len * iters / dt
    metric = ("gpt2_350m_train_tokens_per_sec_per_chip" if on_tpu
              else "gpt_tiny_cpu_smoke_tokens_per_sec")
    # vs_baseline only meaningful against the V100 GPT-350M number when
    # actually running that config on the TPU
    vs = round(tokens_per_sec / 10_000.0, 3) if on_tpu else None
    print(json.dumps({
        "metric": metric,
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec",
        "vs_baseline": vs,
    }))


if __name__ == "__main__":
    main()
