"""Multi-config training benchmark (BASELINE.md configs 1-5).

Headline metric (driver contract, ONE JSON line): GPT-350M-class causal-LM
training tokens/sec/chip, vs_baseline = tokens_per_sec / 10_000 (published
Megatron-era V100 number for a 345M GPT-2: ~9-10k tokens/sec fp16 — 1.0
means V100 parity). The `extras` field carries the other BASELINE configs
(ResNet-50 imgs/sec, BERT-base+LAMB seqs/sec, LeNet fit steps/sec,
Wide&Deep PS examples/sec) each with an approximate MFU against the
v5e chip's 197 TFLOP/s bf16 peak, so the headline can't flatter
(VERDICT r1 weak #9).

Timing method: inputs are device-resident (one transfer), N steps are
chained through donated params, and ONE jax.device_get of the final loss
is the barrier — on the axon relay, block_until_ready can return early
and any per-step host fetch adds ~0.3s of relay round-trip.

A soft time budget drops remaining configs (headline always runs first)
so the driver's harness timeout can't truncate the JSON output.
"""
import json
import time

import numpy as np

PEAK_FLOPS = 197e12  # v5e bf16 peak per chip
BUDGET_S = 555.0     # soft wall-clock budget for the whole suite

_t_start = time.time()


def _budget_left():
    return BUDGET_S - (time.time() - _t_start)


# ----------------------------------------------------------------- gpt


def bench_gpt(on_tpu):
    import jax
    import jax.numpy as jnp
    from paddle_tpu.parallel.hybrid_gpt import GPTConfig, HybridGPT

    dev = jax.devices()[0]
    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, seq_len=1024, d_model=1024,
                        n_heads=16, n_layers=24, dp=1, pp=1, mp=1,
                        micro_batches=1, remat=True, zero_stage=0,
                        # r5 levers (docs/gpt_perf_analysis.md): keep the
                        # splash kernel's (out, lse) residuals across the
                        # block remat, fused bf16 CE (chunked x4 for the
                        # freed logits memory), bf16 grads w/ f32 master
                        remat_policy="save_splash_residuals",
                        fused_ce=True, ce_seq_chunks=4, bf16_grads=True,
                        compute_dtype=jnp.bfloat16)
        batch, iters = 32, 12
    else:
        cfg = GPTConfig(vocab_size=1024, seq_len=128, d_model=128,
                        n_heads=4, n_layers=2, dp=1, pp=1, mp=1,
                        micro_batches=1, remat=False, zero_stage=0,
                        compute_dtype=jnp.float32)
        batch, iters = 4, 3

    trainer = HybridGPT(cfg, devices=[dev])
    params, opt = trainer.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    tok = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, cfg.seq_len)),
                      jnp.int32)
    lab = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, cfg.seq_len)),
                      jnp.int32)
    # compile + 2 warm steps: the relay's first post-compile dispatches
    # run degraded (r4 note) and would bias the timed window low.
    # (A K-step grouped timed window via trainer.train_many measured
    # SLOWER — 39.0k vs 39.4k tok/s: the scan-carried param/opt state
    # costs more than the 12 saved dispatches. Per-step stays.)
    for w in range(3):
        params, opt, loss = trainer.train_step(params, opt, tok, lab,
                                               step_num=w + 1)
        float(jax.device_get(loss))

    # min-of-k timed windows (r6 BASELINE.md host-variance hardening,
    # extended to this lane per ISSUE 7): a host-load spike inside a
    # single window is indistinguishable from a code regression
    step_num = 4
    best = float("inf")
    final_loss = None
    for k in range(3 if on_tpu else 2):
        t0 = time.perf_counter()
        for i in range(iters):
            params, opt, loss = trainer.train_step(params, opt, tok, lab,
                                                   step_num=step_num)
            step_num += 1
        final_loss = float(jax.device_get(loss))
        best = min(best, time.perf_counter() - t0)
        if k and _budget_left() < 300:
            break
    assert np.isfinite(final_loss)

    toks = batch * cfg.seq_len * iters
    tps = toks / best
    from paddle_tpu.profiler import metrics as _metrics
    if _metrics._enabled:
        _metrics.TOKENS_PER_SEC.set(tps)
    # approx train FLOPs/token: 6*N (fwd+bwd weight flops) + causal
    # attention 6*L*S*d
    d, L, S, V = cfg.d_model, cfg.n_layers, cfg.seq_len, cfg.vocab_size
    n_params = 12 * L * d * d + V * d + S * d
    flops_tok = 6 * n_params + 6 * L * S * d
    mfu = tps * flops_tok / PEAK_FLOPS
    step_seconds = best / iters
    return tps, mfu, _tuner_plan_extra(mfu if on_tpu else None,
                                       step_seconds if on_tpu else None)


def _tuner_plan_extra(measured_mfu, measured_step_seconds):
    """auto_tuner placement-search extra (ISSUE 7 acceptance: record the
    tuner's predicted MFU NEXT TO the measured one). The search prices
    the GPT-350M bench config on the 8-chip v5e-ish ClusterSpec;
    calibration uses THIS run's measured single-chip step on TPU, or
    the recorded BENCH_r05 measurement (MFU 0.456) on CPU where the
    tiny smoke config says nothing about the 350M model."""
    try:
        from paddle_tpu.parallel.auto_tuner import (ClusterSpec,
                                                    CostModel, ModelSpec,
                                                    Strategy, tune)
        mspec = ModelSpec(n_layers=24, d_model=1024, seq_len=1024,
                          vocab_size=50304, global_batch=32, n_heads=16)
        single = Strategy()
        meas = {"strategy": single}
        if measured_step_seconds:
            meas["step_seconds"] = measured_step_seconds
            calib_src = "this_run"
        else:
            meas["mfu"] = 0.456          # BENCH_r05 measured single-chip
            calib_src = "bench_r05"
        plan = tune(mspec, cluster=ClusterSpec(), measurements=meas)
        cm = CostModel(plan.cluster)
        pred_single = cm.predicted_mfu(mspec, single)
        return {
            "metric": "auto_tuner_plan",
            "chosen_config": plan.strategy.as_hybrid_configs(),
            "predicted_mfu_8chip": round(plan.predicted_mfu, 4),
            "predicted_step_seconds_8chip": round(plan.step_time, 5),
            "predicted_single_chip_mfu": round(pred_single, 4),
            "measured_single_chip_mfu": (round(measured_mfu, 4)
                                         if measured_mfu else None),
            "calibration_source": calib_src,
            "calibrated_mxu_efficiency": round(
                plan.cluster.mxu_efficiency, 4),
        }
    except Exception as e:  # noqa: BLE001
        return {"metric": "auto_tuner_plan",
                "error": f"{type(e).__name__}: {e}"}


# -------------------------------------------------------------- resnet


def bench_resnet50():
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    import paddle_tpu.amp as amp
    from paddle_tpu.vision.models import resnet50

    net = resnet50(num_classes=1000)
    amp.decorate(net, level="O2")
    model = paddle.Model(net)
    opt = paddle.optimizer.Momentum(
        0.1, parameters=model.parameters(), weight_decay=1e-4)
    model.prepare(opt, paddle.nn.CrossEntropyLoss())

    B, H = 128, 224
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(jnp.asarray(rng.rand(B, 3, H, H), jnp.float32))
    y = paddle.to_tensor(jnp.asarray(rng.randint(0, 1000, (B, 1)),
                                     jnp.int32))
    float(x._data.sum())  # input transfer done

    losses, _ = model._train_batch_inner([x], [y])  # compile
    float(jax.device_get(losses[0]._data))
    assert model._jit_ok, "ResNet-50 compiled path fell back to eager"

    # min-of-2 timed windows (BASELINE.md host-variance hardening,
    # extended to this lane per ISSUE 7)
    iters = 20
    best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        last = None
        for _i in range(iters):
            losses, _ = model._train_batch_inner([x], [y])  # lazy loss
            last = losses[0]
        float(jax.device_get(last._data))  # single honest barrier
        best = min(best, time.perf_counter() - t0)
        if _budget_left() < 120:
            break
    ips = B * iters / best
    # ResNet-50@224 fwd = 4.1 GMACs = 8.2 GFLOPs (2*MAC, same convention
    # as the GPT/BERT 6N formulas); train ~3x fwd. The r1/r2 benches used
    # 4.1e9 here — counting MACs as FLOPs — and so understated MFU 2x.
    flops_img = 3 * 8.2e9
    return ips, ips * flops_img / PEAK_FLOPS


# ---------------------------------------------------------------- bert


def bench_bert():
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    import paddle_tpu.amp as amp
    from paddle_tpu.models import (bert_base, BertForPretraining,
                                   BertPretrainingCriterion)

    bert = bert_base()
    net = BertForPretraining(bert)
    # AMP O2 like the ResNet config (and the reference's fp16 BERT
    # pretrain recipe); r2-r4 ran this config in full f32 — that plus
    # threefry dropout RNG (now rbg on TPU, core/random.py _use_rbg)
    # was the 27.6%-MFU plateau. At S=128 the XLA attention path beats
    # the splash kernel (854 vs 754 seqs/s measured), so the masked
    # splash routing matters for long-S/eval, not this config.
    amp.decorate(net, level="O2")
    crit = BertPretrainingCriterion(bert.vocab_size)
    model = paddle.Model(net)
    opt = paddle.optimizer.Lamb(learning_rate=1e-3,
                                lamb_weight_decay=0.01,
                                parameters=net.parameters())
    model.prepare(opt, crit)

    B, S = 64, 128
    rng = np.random.RandomState(0)
    tok = rng.randint(1, bert.vocab_size, (B, S))
    mlm = rng.randint(0, bert.vocab_size, (B, S))
    mlm[rng.rand(B, S) > 0.15] = -1
    nsp = rng.randint(0, 2, (B,))
    tok_t = paddle.to_tensor(jnp.asarray(tok, jnp.int32))
    mlm_t = paddle.to_tensor(jnp.asarray(mlm, jnp.int32))
    nsp_t = paddle.to_tensor(jnp.asarray(nsp, jnp.int32))
    float(tok_t._data.sum())

    losses, _ = model._train_batch_inner([tok_t], [mlm_t, nsp_t])
    float(jax.device_get(losses[0]._data))
    assert model._jit_ok, "BERT compiled path fell back to eager"

    iters = 20
    t0 = time.perf_counter()
    last = None
    for _ in range(iters):
        losses, _ = model._train_batch_inner([tok_t], [mlm_t, nsp_t])
        last = losses[0]
    float(jax.device_get(last._data))
    dt = time.perf_counter() - t0
    sps = B * iters / dt
    d, L = bert.hidden_size, bert.num_layers
    n_params = 12 * L * d * d + bert.vocab_size * d
    flops_seq = (6 * n_params + 12 * L * S * d) * S
    return sps, sps * flops_seq / PEAK_FLOPS


# --------------------------------------------------------------- lenet


def bench_lenet():
    import paddle_tpu as paddle
    from paddle_tpu.vision.models import LeNet
    from paddle_tpu.vision.datasets import MNIST

    model = paddle.Model(LeNet())
    opt = paddle.optimizer.Adam(1e-3, parameters=model.parameters())
    model.prepare(opt, paddle.nn.CrossEntropyLoss())
    # 32-step dispatch groups: per-call relay latency (8-100 ms
    # depending on link health) would otherwise dominate a sub-ms model
    model._fit_group_max = 32
    ds = MNIST(mode="train", synthetic_size=4096)
    # device-cached input pipeline: MNIST fits in HBM, so epochs past
    # the first stream with zero host->device transfers (the TPU-first
    # input pattern; the relay's h2d link is otherwise the bottleneck)
    from paddle_tpu.io import DataLoader, DeviceCacheLoader
    loader = DeviceCacheLoader(DataLoader(ds, batch_size=64,
                                          shuffle=True))
    fit_kw = dict(epochs=1, batch_size=64, verbose=0, log_freq=32)
    model.fit(loader, **fit_kw)  # warm/compile + fill the device cache
    # min-of-3 epochs: this config is fit-loop/host bound and the
    # BASELINE.md r4->r5 A/B showed host-load spikes swing it 3x+ while
    # real deltas were <1% — a single timed epoch is relay-noise
    # roulette (same hardening the int8 B=1 ratio got in r5)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        model.fit(loader, **fit_kw)
        best = min(best, time.perf_counter() - t0)
        if _budget_left() < 90:
            break
    steps = 4096 // 64
    return steps / best, None  # steps/sec (fit-loop bound, no MFU)


# ----------------------------------------------------------- wide&deep


def _load_wd_example():
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "wd_example",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "examples", "5_wide_deep_ps.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def bench_wide_deep():
    """Config 5: embedding pull -> dense train -> push through the native
    PS engine (C++ sharded tables), examples/sec + training AUC."""
    mod = _load_wd_example()
    if not hasattr(mod, "run_bench"):
        return None, None
    # min-of-2 full runs (host-variance hardening per BASELINE.md:
    # the r5 "-11%" was relay/host load, not code): keep the faster
    # run's examples/sec and its AUC, budget permitting
    eps, auc = mod.run_bench()
    if _budget_left() > 120:
        eps2, auc2 = mod.run_bench()
        if eps2 > eps:
            eps, auc = eps2, auc2
    return eps, None, {"metric": "wide_deep_train_auc",
                       "value": round(auc, 4), "unit": "auc"}


def bench_graph_sage():
    """GraphSAGE over the sharded graph engine (ps/graph: hash-
    partitioned adjacency co-located with the embedding shards,
    per-hop frontier dedup, deterministic fixed-shape sampling, bundle
    prefetch, stream-mode feature engine) with RPC-backed features, vs
    the plain sequential order of operations (raw-frontier sampling —
    every duplicate node re-sampled each hop — plus full raw-bundle
    RemoteSparseTable pull/push per batch: no dedup, no cache, no
    prefetch) against the same localhost parameter servers. The two
    lanes produce bit-identical training (the sampler is pure per
    (node, seed)). CPU-capable; the driver contract is engine >= 1.2x
    sequential.

    On the 1-core CPU box thread overlap conserves CPU, so the honest
    speedup source is WORK REDUCTION — above all the frontier dedup:
    the bundle is ~71% duplicate keys (power-law hubs + mask padding)
    and shard-side sampling cost scales with the edges gathered for
    queried rows, so the naive lane pays ~5x the sampling work; the
    raw-bundle wire path adds more (docs/GRAPH.md has the
    decomposition and the expected multi-core/TPU overlap effect)."""
    import numpy as np

    from paddle_tpu.ps import (GraphEngine, HeterEmbeddingEngine,
                               ShardedGraphTable)
    from paddle_tpu.ps.graph import (SageTrainer, contrastive_batches,
                                     make_power_law_graph)
    from paddle_tpu.ps.service import (PSClient, PSServer,
                                       RemoteSparseTable)

    dim, bsz, steps, nodes = 64, 128, 16, 20000
    src, dst = make_power_law_graph(num_nodes=nodes, avg_degree=8,
                                    seed=3)
    ids = np.arange(1, nodes + 1, dtype=np.uint64)
    batches = contrastive_batches(src, dst, ids, batch_size=bsz,
                                  steps=steps, seed=5)

    from paddle_tpu.ps.graph.engine import GraphEngine as _GE

    class NaiveGraphEngine(_GE):
        """Plain order of operations: sample the RAW frontier each hop
        (no per-hop np.unique — duplicate nodes are sampled again, as
        a straightforward per-node loop would). Output is BIT-IDENTICAL
        to the deduped engine (the sampler is pure per (node, seed));
        the dedup is pure work-reduction, which is what this lane
        measures the absence of."""

        def _sample_hops(self, seeds, batch_seed):
            neighbors, masks = [], []
            uniqs = [np.unique(seeds)]
            frontier = seeds
            raw = 0
            for h, f in enumerate(self.fanouts):
                raw += frontier.size
                nb, mk = self.graph.sample_neighbors(
                    frontier, f,
                    seed=(batch_seed + h) & 0xFFFFFFFFFFFFFFFF)
                neighbors.append(nb)
                masks.append(mk)
                frontier = nb.reshape(-1)
                uniqs.append(np.unique(frontier))
            node_union = np.unique(np.concatenate(uniqs))
            return (tuple(neighbors), tuple(masks), node_union, raw,
                    raw)

    class DirectFeatures:
        """Plain order of operations: sync full-raw-bundle RPC pull
        and push, duplicates and all."""

        def __init__(self, table):
            self.table = table
            self.dim = table.dim

        def pull(self, keys, train=False, use_prefetch=False):
            return self.table.pull(np.asarray(keys).reshape(-1))

        def push(self, keys, grads):
            return self.table.push(np.asarray(keys).reshape(-1),
                                   grads)

        def flush(self):
            return self

        def state(self):
            return {"direct": True}

    def make_lane(pipelined):
        servers = [PSServer() for _ in range(2)]
        for s in servers:
            s.register_sparse_table(0, dim=dim, sgd_rule="sgd",
                                    learning_rate=0.5)
            s.run(background=True)
        client = PSClient([f"127.0.0.1:{s.port}" for s in servers])
        table = RemoteSparseTable(client, 0, dim=dim)
        # stream-mode features (bounded-staleness async-SGD, the
        # wide_deep_heter bench lane's mode): resident rows accumulate
        # merged deltas in the cache and write back on eviction/
        # staleness/flush instead of strict's synchronous push +
        # re-read round trip per batch. The parity gates
        # (tools/graph_smoke.py, tests) run strict.
        feats = (HeterEmbeddingEngine(table, cache_capacity=16384,
                                      mode="stream", staleness_bound=8,
                                      prefetch=True)
                 if pipelined else DirectFeatures(table))
        graph = ShardedGraphTable(num_shards=2)
        graph.add_edges(src, dst)
        cls = GraphEngine if pipelined else NaiveGraphEngine
        eng = cls(graph, features=feats, fanouts=(10, 5),
                  mode="strict", base_seed=7,
                  prefetch=pipelined)
        tr = SageTrainer(eng, hidden_dims=(32, 16), lr=0.5,
                         param_seed=0)

        def one_pass():
            t0 = time.perf_counter()
            for i, (c, p, n) in enumerate(batches):
                tr.train_step(c, p, n)
                if pipelined and i + 1 < steps:
                    tr.prefetch(*batches[i + 1])
            eng.flush()
            return time.perf_counter() - t0

        def close():
            st = eng.state()
            eng.close()
            client.close()
            for s in servers:
                s.stop()
            return st
        return one_pass, close

    # Both lanes stay live and alternate timed passes so host drift
    # over the lane's window hits them equally (the serving lanes'
    # best-of-3 interleaved discipline). A flushed lane is quiescent
    # between passes, so the idle one doesn't steal the timed one's
    # core.
    direct_pass, direct_close = make_lane(False)
    engine_pass, engine_close = make_lane(True)
    direct_pass()                           # warmup/compile
    engine_pass()
    dts_e, dts_d = [], []
    for _ in range(3):
        dts_e.append(engine_pass())
        dts_d.append(direct_pass())
    direct_close()
    st = engine_close()
    direct_eps = bsz * steps / min(dts_d)
    engine_eps = bsz * steps / min(dts_e)
    return {"metric": "graph_sage_examples_per_sec",
            "value": round(engine_eps, 1), "unit": "examples/sec",
            "direct_examples_per_sec": round(direct_eps, 1),
            "speedup_vs_direct": round(engine_eps / direct_eps, 3),
            "dedup_ratio": st["dedup_ratio"],
            "prefetch": st["prefetch"],
            "fanouts": st["fanouts"],
            "graph_nodes": st["graph_nodes"],
            "graph_edges": st["graph_edges"]}


def bench_wide_deep_heter():
    """HeterPS-style embedding engine (ps/heter: hot-ID cache +
    prefetch pipeline + dedup-merged background push) vs the direct
    RemoteSparseTable lane, both against real parameter servers over
    localhost RPC on a zipf key stream. CPU-capable; the driver
    contract is engine >= 1.3x direct."""
    engine_eps, direct_eps, stats = _load_wd_example().run_bench_heter()
    return {"metric": "wide_deep_heter_examples_per_sec",
            "value": round(engine_eps, 1), "unit": "examples/sec",
            "direct_examples_per_sec": round(direct_eps, 1),
            "speedup_vs_direct": round(engine_eps / direct_eps, 3),
            "cache_hit_ratio": stats["cache_hit_ratio"],
            "dedup_ratio": stats["dedup_ratio"],
            "prefetch": stats["prefetch"]}


# -------------------------------------------------------------- decode


def bench_decode():
    """LLM serving decode: GPT2-350M-class FusedMultiTransformer stack,
    weight-only int8, fixed-shape KV cache, compiled scan decode
    (reference capability: `fused_multi_transformer_op.cu` + cache_kvs).
    tokens/sec = generated tokens (prefill amortized in)."""
    import jax
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.models.gpt import GPTForGeneration

    def _decode_tps(m, B, T=128, reps=1):
        P = 128
        rng = np.random.RandomState(0)
        ids = Tensor(rng.randint(0, 50304, (B, P)).astype(np.int32))
        out, _ = m.generate(ids, max_new_tokens=T)  # compile + warm
        np.asarray(out.numpy())
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            out, _ = m.generate(ids, max_new_tokens=T)
            np.asarray(out.numpy())
            best = min(best, time.perf_counter() - t0)
        return B * T / best

    def run(weight_only, B, T=128, reps=1):
        m = GPTForGeneration(vocab_size=50304, hidden_size=1024,
                             num_layers=24, num_attention_heads=16,
                             max_position_embeddings=2048,
                             compute_dtype="bfloat16",
                             weight_only=weight_only)
        m.eval()
        return m, _decode_tps(m, B, T, reps)

    m64, tps = run(True, 64)
    # the weight-only-int8 REGIME win: B=1 serving is
    # weight-bandwidth-bound (int8 halves HBM reads); at B>=8 the
    # KV cache + per-step kernel latency dominate and int8 ~ bf16
    # (docs/decode_int8_analysis.md). This extra must land in the
    # driver run (VERDICT r4 #4) — only a FAILURE (not the budget)
    # may drop it, and failure must not lose the headline. Full
    # T=128 horizon: a shorter decode dilutes the ratio with the
    # (identical) prefill cost — measured 1.10x at T=64 vs 1.26x+
    # at T=128.
    try:
        # min-of-5 per side: the B=1 ratio is dispatch-latency-bound
        # and a single host-load spike measured it at 1.03x (vs the
        # quiet-machine 1.24-1.34x)
        i8 = _decode_tps(m64, 1, reps=5)  # same weights, new batch
        del m64
        import gc
        gc.collect()
        _, b16 = run(False, 1, reps=5)
        extra = {"metric": "gpt2_350m_decode_int8_speedup_b1",
                 "value": round(i8 / b16, 3), "unit": "x vs bf16"}
    except Exception as e:  # noqa: BLE001
        extra = {"metric": "gpt2_350m_decode_int8_speedup_b1",
                 "error": f"{type(e).__name__}: {e}"}
    return tps, None, extra  # bandwidth-bound; MFU not meaningful


def bench_decode_speculative():
    """ISSUE 3 extra: latency-bound decode with the scanned fused step
    and n-gram speculative verification, B=1 and B=8, on repetitive/
    greedy text (cyclic prompt pattern -> the prompt-lookup draft can
    actually land; acceptance is reported so the number can't hide a
    draft that never hits). tokens/sec counts GENERATED tokens over the
    full generate() wall time, same convention as bench_decode. The r5
    B=1 bf16 baseline for this config was 465 tok/s with the unrolled
    decode step."""
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.models.gpt import GPTForGeneration

    m = GPTForGeneration(vocab_size=50304, hidden_size=1024,
                         num_layers=24, num_attention_heads=16,
                         max_position_embeddings=2048,
                         compute_dtype="bfloat16")
    m.eval()
    P, T = 128, 128
    pattern = np.arange(7, 23, dtype=np.int32)     # 16-token cycle

    def run(B, draft_k, reps=3):
        ids = Tensor(np.tile(pattern, (B, P // len(pattern))))
        kw = dict(max_new_tokens=T, draft_k=draft_k)
        out, _ = m.generate(ids, **kw)             # compile + warm
        np.asarray(out.numpy())
        best = float("inf")
        accept = None
        for _ in range(reps):
            t0 = time.perf_counter()
            out, _ = m.generate(ids, **kw)
            np.asarray(out.numpy())
            best = min(best, time.perf_counter() - t0)
            if draft_k:
                steps = m.last_accept_counts
                tot = sum(sum(s) for s in steps)
                accept = tot / max(1, sum(len(s) for s in steps))
        return B * T / best, accept

    b1_scan, _ = run(1, 0)             # scanned fused step, no drafts
    b1_spec, b1_acc = run(1, 7)
    extra = {
        "metric": "gpt2_350m_decode_speculative_detail",
        "b1_scan_tokens_per_sec": round(b1_scan, 1),
        "b1_spec_tokens_per_sec": round(b1_spec, 1),
        "b1_mean_accept": round(b1_acc, 2) if b1_acc else None,
        "b1_vs_r5_unrolled_465": round(max(b1_scan, b1_spec) / 465.0, 3),
    }
    if _budget_left() > 120:           # B=8 pair is two more compiles
        b8_scan, _ = run(8, 0)
        b8_spec, b8_acc = run(8, 7)
        extra.update(
            b8_scan_tokens_per_sec=round(b8_scan, 1),
            b8_spec_tokens_per_sec=round(b8_spec, 1),
            b8_mean_accept=round(b8_acc, 2) if b8_acc else None)
    else:
        extra["b8_skipped"] = "time budget"
    # headline = the SPECULATIVE number (the metric's name): a draft
    # path slower than plain scan must show up as a regression, not be
    # papered over by max(); the scan baseline and the best-of ratio
    # ride in the detail extra
    return b1_spec, None, extra


def bench_serving():
    """Continuous batching (paddle_tpu.serving) vs sequential
    one-request-at-a-time generation.py on the SAME synthetic Poisson
    request stream (tiny GPT — runs on CPU too). Driver contract:
    speedup_vs_sequential >= 2.0 sustained, mixed_step_compiles == 1
    across the whole run (admissions/evictions never retrace)."""
    import time as _time

    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.models.gpt import GPTForGeneration
    from paddle_tpu.profiler import metrics as pm
    from paddle_tpu.serving.batcher import next_pow2
    from paddle_tpu.serving.engine import ServingEngine, STEP_FN_NAME

    rng = np.random.RandomState(0)
    V, T_new, N = 1024, 16, 24
    m = GPTForGeneration(vocab_size=V, hidden_size=128, num_layers=2,
                         num_attention_heads=4,
                         max_position_embeddings=512,
                         compute_dtype="float32")
    m.eval()
    lens = rng.randint(4, 40, N)
    prompts = [rng.randint(1, V, int(n)).astype(np.int32) for n in lens]
    arrivals = np.cumsum(rng.exponential(0.002, N))  # Poisson stream
    arrivals -= arrivals[0]

    was_enabled = pm._enabled
    pm.enable()
    try:
        eng = ServingEngine(m, max_slots=8, block_size=16,
                            max_seq_len=128, cache_dtype="float32",
                            seed=0)
        # warm: compiles the ONE mixed step; the timed stream reuses it
        eng.generate_batch([prompts[0]], max_new_tokens=2)

        t0 = _time.perf_counter()
        pending = list(zip(prompts, arrivals))
        reqs = []
        while pending or eng.scheduler.has_work:
            now = _time.perf_counter() - t0
            while pending and pending[0][1] <= now:
                p, _ = pending.pop(0)
                reqs.append(eng.submit(p, T_new))
            if not eng.step() and pending:
                _time.sleep(max(0.0, pending[0][1]
                                 - (_time.perf_counter() - t0)))
        serve_wall = _time.perf_counter() - t0
        served_tokens = sum(len(r.output) for r in reqs)
        lat = sorted(r.finish_time - r.submit_time for r in reqs)
        compiles = pm.JIT_COMPILES.labels(STEP_FN_NAME).value
        preempts = eng.scheduler.preemption_count
    finally:
        if not was_enabled:
            pm.disable()

    # sequential baseline: one generate() per request in arrival order,
    # started at max(arrival, previous finish); warm each prompt bucket
    # so neither side pays compiles inside the timed region
    for b in sorted({next_pow2(len(p)) for p in prompts}):
        m.generate(Tensor(np.ones((1, b), np.int32)),
                   max_new_tokens=T_new, cache_dtype="float32")
    t = 0.0
    finish = []
    for p, a in zip(prompts, arrivals):
        s0 = _time.perf_counter()
        out, _ = m.generate(Tensor(np.asarray(p)[None]),
                            max_new_tokens=T_new, cache_dtype="float32")
        np.asarray(out.numpy())
        dt = _time.perf_counter() - s0
        t = max(t, a) + dt
        finish.append(t)
    seq_tokens = N * T_new
    seq_tput = float(seq_tokens / (finish[-1] - arrivals[0]))
    serve_tput = float(served_tokens / serve_wall)
    return {
        "metric": "serving_continuous_batching",
        "value": round(serve_tput, 1), "unit": "tokens/sec",
        "sequential_tokens_per_sec": round(seq_tput, 1),
        "speedup_vs_sequential": round(serve_tput / seq_tput, 3),
        "p50_latency_s": round(lat[len(lat) // 2], 4),
        "p99_latency_s": round(lat[min(len(lat) - 1,
                                       int(len(lat) * 0.99))], 4),
        "requests": N, "mixed_step_compiles": int(compiles),
        "preemptions": int(preempts),
    }


def bench_serving_multitick(n_requests=16, t_new=65):
    """Device-resident multi-tick decode (ISSUE 18): the SAME Poisson
    stream served at ticks_per_dispatch 1, 4 and 8 — decode tokens/sec
    and inter-token p50/p99 vs N — plus a host-stall-share record for
    the async-device_get runtime (sync readback vs overlapped) at N=8.
    Driver contract: decode tok/s strictly improves N=1 -> N=8 (the
    host dispatch wall is the inter-token floor the while_loop
    removes), every engine compiles its mixed step exactly once, and
    outputs stay token-identical across N."""
    import time as _time

    from paddle_tpu.models.gpt import GPTForGeneration
    from paddle_tpu.profiler import metrics as pm
    from paddle_tpu.serving.engine import ServingEngine, STEP_FN_NAME

    rng = np.random.RandomState(0)
    V = 1024
    m = GPTForGeneration(vocab_size=V, hidden_size=128, num_layers=2,
                         num_attention_heads=4,
                         max_position_embeddings=512,
                         compute_dtype="float32")
    m.eval()
    lens = rng.randint(4, 40, n_requests)
    prompts = [rng.randint(1, V, int(n)).astype(np.int32)
               for n in lens]
    arrivals = np.cumsum(rng.exponential(0.002, n_requests))
    arrivals -= arrivals[0]

    def stream(eng):
        pending = list(zip(prompts, arrivals))
        reqs, seen, gaps = [], {}, []
        stall0 = eng.host_stall_total
        t0 = _time.perf_counter()
        while pending or eng.scheduler.has_work:
            now = _time.perf_counter() - t0
            while pending and pending[0][1] <= now:
                p, _ = pending.pop(0)
                reqs.append(eng.submit(p, t_new))
            if not eng.step() and pending:
                _time.sleep(max(0.0, pending[0][1]
                                 - (_time.perf_counter() - t0)))
                continue
            now = _time.perf_counter() - t0
            # inter-token gaps, dispatch-granular: a k-token harvest
            # contributes k gaps of (now - last)/k — the stream rate a
            # client consuming the staging buffer actually sees
            for r in reqs:
                i = id(r)
                have = len(r.output)
                last_n, last_t = seen.get(i, (0, None))
                if have > last_n:
                    if last_t is not None:
                        gaps += [(now - last_t) / (have - last_n)] \
                            * (have - last_n)
                    seen[i] = (have, now)
        wall = _time.perf_counter() - t0
        toks = sum(len(r.output) for r in reqs)
        gaps.sort()
        return {
            "outputs": [list(r.output) for r in reqs],
            "tok_s": toks / wall, "wall": wall,
            "itl_p50_ms": gaps[len(gaps) // 2] * 1e3 if gaps else 0.0,
            "itl_p99_ms": gaps[min(len(gaps) - 1,
                                   int(len(gaps) * 0.99))] * 1e3
            if gaps else 0.0,
            "stall_share": (eng.host_stall_total - stall0) / wall,
        }

    def build(n_ticks, multitick_async=True):
        eng = ServingEngine(m, max_slots=8, block_size=16,
                            max_seq_len=128, cache_dtype="float32",
                            seed=0, ticks_per_dispatch=n_ticks,
                            multitick_async=multitick_async)
        c0 = pm.JIT_COMPILES.labels(STEP_FN_NAME).value
        # warm: compiles the ONE mixed step (while_loop included);
        # the timed streams below reuse it
        eng.generate_batch([prompts[0]], max_new_tokens=2)
        return eng, int(pm.JIT_COMPILES.labels(STEP_FN_NAME).value
                        - c0)

    def serve_all(keys, passes=3):
        """Best-of-`passes` per engine, passes INTERLEAVED across the
        engines: the single-core harness drifts by 10-20% over seconds
        (enough to drown the dispatch-wall signal), and round-robin
        spreads any slow window over every N instead of sinking one."""
        engines = {k: build(*k) for k in keys}
        runs = {k: [] for k in keys}
        for _ in range(passes):
            for k in keys:
                runs[k].append(stream(engines[k][0]))
        out = {}
        for k in keys:
            best = max(runs[k], key=lambda r: r["tok_s"])
            if any(r["outputs"] != best["outputs"] for r in runs[k]):
                best["outputs"] = None  # nondeterminism across passes
            best["eng"], best["compiles"] = engines[k]
            out[k] = best
        return out

    was_enabled = pm._enabled
    pm.enable()
    try:
        keys = [(1, True), (4, True), (8, True), (8, False)]
        res = serve_all(keys)
        by_n = {n: res[(n, True)] for n in (1, 4, 8)}
        sync8 = res[(8, False)]
    finally:
        if not was_enabled:
            pm.disable()
    identical = all(by_n[n]["outputs"] == by_n[1]["outputs"]
                    for n in (4, 8))
    e8 = by_n[8]["eng"]
    return {
        "metric": "serving_multitick",
        "value": round(by_n[8]["tok_s"], 1), "unit": "tokens/sec",
        "decode_tok_s_by_n": {
            str(n): round(by_n[n]["tok_s"], 1) for n in (1, 4, 8)},
        "itl_p50_ms_by_n": {
            str(n): round(by_n[n]["itl_p50_ms"], 3)
            for n in (1, 4, 8)},
        "itl_p99_ms_by_n": {
            str(n): round(by_n[n]["itl_p99_ms"], 3)
            for n in (1, 4, 8)},
        "speedup_n8_vs_n1": round(by_n[8]["tok_s"]
                                  / by_n[1]["tok_s"], 3),
        "host_stall_share_sync": round(sync8["stall_share"], 4),
        "host_stall_share_async": round(by_n[8]["stall_share"], 4),
        "ticks_per_dispatch_mean_n8": round(
            e8.device_ticks_run / max(e8.dispatches_run, 1), 2),
        "early_exits_n8": dict(e8.early_exit_counts),
        "outputs_identical_across_n": bool(identical),
        "mixed_step_compiles": max(r["compiles"]
                                   for r in by_n.values()),
        "requests": n_requests,
    }


def bench_serving_spec_multitick(n_requests=8, t_new=64):
    """On-device speculation lane (ISSUE 19): draft_k=3 speculative
    decode INSIDE the ticks_per_dispatch=8 while_loop vs BOTH
    baselines it must beat — the same speculation at N=1 (host
    drafter, dispatch wall back) and no speculation at N=8 (loop
    without drafts). The tiny GPT is first fit for a few epochs on a
    synthetic copy corpus (repeated short motifs): prompt-lookup
    drafting pays off exactly when the model's own continuations copy
    local context (induction), and a random-weight model has none of
    that — its ~10% accept rate measures nothing but verify overhead.
    Prompts are the same short repeating motifs, so the n-gram
    drafter lands accepts; greedy decode keeps all three
    configurations token-identical, which the record asserts.
    Best-of-3 per engine, passes interleaved (same drift discipline
    as bench_serving_multitick). Driver contract: spec-N8 tok/s
    strictly above spec-N1 AND above nospec-N8, one mixed-step
    compile per engine, accept rate recorded."""
    import time as _time

    import paddle_tpu as paddle
    from paddle_tpu.io import TensorDataset
    from paddle_tpu.models.gpt import (GPTForGeneration, GPTModel,
                                       GPTForPretraining,
                                       GPTPretrainingCriterion)
    from paddle_tpu.profiler import metrics as pm
    from paddle_tpu.serving.engine import ServingEngine, STEP_FN_NAME

    rng = np.random.RandomState(0)
    V = 1024
    paddle.seed(0)
    net = GPTForPretraining(GPTModel(vocab_size=V, hidden_size=128,
                                     num_layers=2,
                                     num_attention_heads=4,
                                     max_position_embeddings=512))
    trainer = paddle.Model(net)
    trainer.prepare(paddle.optimizer.AdamW(
        3e-3, parameters=trainer.parameters()),
        GPTPretrainingCriterion())
    crng = np.random.RandomState(1)
    seqs = []
    for _ in range(256):
        motif = crng.randint(1, V, int(crng.randint(2, 5)))
        seqs.append(np.tile(motif, 65 // len(motif) + 1)[:65])
    seqs = np.stack(seqs).astype(np.int32)
    trainer.fit(TensorDataset([seqs[:, :-1], seqs[:, 1:]]), epochs=4,
                batch_size=32, verbose=0)
    m = GPTForGeneration.from_pretraining(net)
    m.eval()
    prompts = []
    for _ in range(n_requests):
        motif = rng.randint(1, V, int(rng.randint(2, 5))).tolist()
        prompts.append((motif * (24 // len(motif) + 1))[:24])

    def build(draft_k, n_ticks):
        eng = ServingEngine(m, max_slots=8, block_size=16,
                            max_seq_len=128, cache_dtype="float32",
                            seed=0, draft_k=draft_k,
                            ticks_per_dispatch=n_ticks)
        c0 = pm.JIT_COMPILES.labels(STEP_FN_NAME).value
        eng.generate_batch([prompts[0]], max_new_tokens=2)  # warm
        return eng, int(pm.JIT_COMPILES.labels(STEP_FN_NAME).value
                        - c0)

    def run(eng):
        p0, a0 = eng.spec_proposed_total, eng.spec_accepted_total
        t0 = _time.perf_counter()
        outs = eng.generate_batch(prompts, max_new_tokens=t_new)
        wall = _time.perf_counter() - t0
        toks = sum(len(o) for o in outs)
        return {"outputs": outs, "tok_s": toks / wall, "wall": wall,
                "proposed": eng.spec_proposed_total - p0,
                "accepted": eng.spec_accepted_total - a0}

    was_enabled = pm._enabled
    pm.enable()
    try:
        keys = {"spec_n8": (3, 8), "spec_n1": (3, 1),
                "nospec_n8": (0, 8)}
        engines = {k: build(*v) for k, v in keys.items()}
        runs = {k: [] for k in keys}
        for _ in range(3):
            for k in keys:
                runs[k].append(run(engines[k][0]))
        best = {k: max(runs[k], key=lambda r: r["tok_s"])
                for k in keys}
    finally:
        if not was_enabled:
            pm.disable()
    identical = all(best[k]["outputs"] == best["spec_n1"]["outputs"]
                    for k in keys)
    # accept rate over ALL passes of the spec-N8 engine (per-pass
    # counts are small enough to be noisy)
    prop = sum(r["proposed"] for r in runs["spec_n8"])
    acc = sum(r["accepted"] for r in runs["spec_n8"])
    e8 = engines["spec_n8"][0]
    return {
        "metric": "serving_spec_multitick",
        "value": round(best["spec_n8"]["tok_s"], 1),
        "unit": "tokens/sec",
        "decode_tok_s": {k: round(best[k]["tok_s"], 1) for k in keys},
        "speedup_vs_spec_n1": round(best["spec_n8"]["tok_s"]
                                    / best["spec_n1"]["tok_s"], 3),
        "speedup_vs_nospec_n8": round(best["spec_n8"]["tok_s"]
                                      / best["nospec_n8"]["tok_s"],
                                      3),
        "accept_rate": round(acc / max(prop, 1), 4),
        "drafts_proposed": int(prop), "drafts_accepted": int(acc),
        "draft_k": 3,
        "speculation_mode_n8": e8.speculation_mode,
        "ticks_per_dispatch_mean_n8": round(
            e8.device_ticks_run / max(e8.dispatches_run, 1), 2),
        "outputs_identical": bool(identical),
        "mixed_step_compiles": max(c for _, c in engines.values()),
        "requests": n_requests,
    }


def bench_serving_disagg():
    """ISSUE 13 extra: disaggregated prefill/decode fleet vs a
    monolithic fleet at EQUAL chip count (2 tiny-GPT engines each,
    every platform) under a mixed long-prompt/short-decode Poisson
    stream — the interference workload. The monolithic replicas need a
    prompt-throughput token budget, so EVERY step (decode-only ones
    included) pays the full [T] compute; the disaggregated decode
    replica runs a decode-sized budget and never shares a step with a
    prefill burst, which is where the inter-token p99 (the
    interference metric) and TTFT move. Outputs are asserted
    token-identical between the fleets (both greedy), so neither side
    can win by dropping work; migrated-block transport volume rides
    the record."""
    import asyncio
    import time as _time

    from paddle_tpu.models.gpt import GPTForGeneration
    from paddle_tpu.serving.distributed import ReplicaRouter
    from paddle_tpu.serving.engine import ServingEngine
    from paddle_tpu.serving.frontend import ServingFrontend

    rng = np.random.RandomState(0)
    V, T_new, N = 1024, 32, 18
    m = GPTForGeneration(vocab_size=V, hidden_size=128, num_layers=2,
                         num_attention_heads=4,
                         max_position_embeddings=512,
                         compute_dtype="float32")
    m.eval()
    # 1/3 long prompts (the interference source), 2/3 short
    # decode-dominated requests
    prompts = [rng.randint(1, V, 120 if i % 3 == 0 else
                           int(rng.randint(6, 14))).tolist()
               for i in range(N)]
    # steady-state Poisson: long prompts keep ARRIVING throughout the
    # run (per-prefill interference), rather than one opening burst
    # that a 1-core harness would serialize into a pile-up
    arrivals = np.cumsum(rng.exponential(0.05, N))
    arrivals -= arrivals[0]
    # prompt-throughput token budget: one chunk covers a long prompt
    # (the standard chunked-prefill tuning for TTFT) — which is exactly
    # what makes EVERY monolithic step, decode-only ones included, pay
    # the big [T] compute
    BUDGET = 128

    def _warm_transfers(eng):
        # compile the export/import gather/scatter executables (one
        # per pow2 id-width) outside the timed window, same discipline
        # as the mixed-step warm-up
        ids = eng.kv.allocator.alloc(8)
        for w in (1, 2, 4, 8):
            eng.kv.import_blocks(ids[:w], eng.kv.export_blocks(ids[:w]))
        eng.kv.allocator.free(ids)

    def _mono_fleet():
        fes = []
        for _ in range(2):
            eng = ServingEngine(m, max_slots=6, block_size=16,
                                max_seq_len=256, cache_dtype="float32",
                                seed=0, token_budget=BUDGET)
            eng.generate_batch([prompts[1][:4]], max_new_tokens=2)
            fes.append(ServingFrontend(eng, max_pending=32))
        return ReplicaRouter(fes, probe_interval=0.05), fes

    def _disagg_fleet():
        pre = ServingEngine(m, max_slots=6, block_size=16,
                            max_seq_len=256, cache_dtype="float32",
                            seed=0, role="prefill", token_budget=BUDGET)
        # decode slots are cheap at a decode-sized budget: twice the
        # monolithic slot count still runs a 4x smaller step, and every
        # handed-off request admits without waiting a drain cycle
        dec = ServingEngine(m, max_slots=12, block_size=16,
                            max_seq_len=256, cache_dtype="float32",
                            seed=0, role="decode")
        for eng in (pre, dec):
            # max_new_tokens=1 finishes AT the first token, so the
            # warm-up request never parks in the handoff state
            eng.generate_batch([prompts[1][:4]], max_new_tokens=1)
            _warm_transfers(eng)
        fes = [ServingFrontend(e, max_pending=32) for e in (pre, dec)]
        return ReplicaRouter(fes, roles=["prefill", "decode"],
                             probe_interval=0.05), fes

    def _drive(router):
        ttfts, gaps, outs = [None] * N, [[] for _ in range(N)], \
            [None] * N

        async def fire(i, t0):
            delay = arrivals[i] - (_time.perf_counter() - t0)
            if delay > 0:
                await asyncio.sleep(delay)
            sent = _time.perf_counter()
            toks, last = [], None
            async for tok in router.stream(prompts[i],
                                           max_new_tokens=T_new):
                now = _time.perf_counter()
                if last is None:
                    ttfts[i] = now - sent
                else:
                    gaps[i].append(now - last)
                last = now
                toks.append(tok)
            outs[i] = toks

        async def run():
            async with router:
                t0 = _time.perf_counter()
                await asyncio.gather(*[fire(i, t0) for i in range(N)])
                return _time.perf_counter() - t0

        wall = asyncio.run(run())
        flat = sorted(g for gs in gaps for g in gs)
        served = sum(len(o) for o in outs)

        def pct(q):
            return flat[min(len(flat) - 1, int(len(flat) * q))]

        return {
            "tokens_per_sec": round(served / wall, 1),
            "ttft_p50_s": round(sorted(ttfts)[N // 2], 4),
            "inter_token_p50_s": round(pct(0.50), 4),
            "inter_token_p99_s": round(pct(0.99), 4),
        }, outs

    mono_router, _ = _mono_fleet()
    mono, mono_outs = _drive(mono_router)
    dis_router, _ = _disagg_fleet()
    dis, dis_outs = _drive(dis_router)
    assert dis_outs == mono_outs, \
        "disaggregated outputs diverge from the monolithic fleet"
    st = dis_router.stats()
    return {
        "metric": "serving_disagg",
        # headline: the interference metric the split exists to fix
        "value": round(mono["inter_token_p99_s"]
                       / max(dis["inter_token_p99_s"], 1e-9), 2),
        "unit": "x_p99_inter_token_improvement",
        "monolithic_2x": mono,
        "disagg_1p1d": dis,
        "tokps_ratio_disagg_vs_mono": round(
            dis["tokens_per_sec"] / mono["tokens_per_sec"], 3),
        "requests": N, "max_new_tokens": T_new,
        "outputs_identical": True,
        "migrations": st["migrations"],
        "migrated_blocks": st["transport"]["blocks_sent"],
        "migrated_bytes": st["transport"]["bytes_sent"],
    }


def bench_serving_router():
    """ISSUE 8 extra: 2-replica `ReplicaRouter` under a Poisson
    multi-tenant shared-prefix stream (tiny GPT, every platform) —
    aggregate tokens/sec across replicas, prefix-affinity hit ratio,
    and the failover count after a forced replica crash at ~60% of the
    stream (the surviving replica must finish the in-flight requests
    with greedy-identical outputs, so served tokens stay exact)."""
    import asyncio
    import time as _time

    from paddle_tpu.models.gpt import GPTForGeneration
    from paddle_tpu.serving.distributed import ReplicaRouter
    from paddle_tpu.serving.engine import ServingEngine
    from paddle_tpu.serving.frontend import ServingFrontend

    rng = np.random.RandomState(0)
    V, T_new, N = 1024, 16, 24
    m = GPTForGeneration(vocab_size=V, hidden_size=128, num_layers=2,
                         num_attention_heads=4,
                         max_position_embeddings=512,
                         compute_dtype="float32")
    m.eval()
    heads = [rng.randint(1, V, 32).tolist() for _ in range(2)]
    fams = rng.randint(0, 2, N)
    prompts = [heads[f] + rng.randint(1, V, int(n)).tolist()
               for f, n in zip(fams, rng.randint(4, 24, N))]
    arrivals = np.cumsum(rng.exponential(0.004, N))
    arrivals -= arrivals[0]

    fes = []
    for _ in range(2):
        eng = ServingEngine(m, max_slots=6, block_size=16,
                            max_seq_len=128, cache_dtype="float32",
                            seed=0, prefix_caching=True)
        eng.generate_batch([prompts[0][:4]], max_new_tokens=2)  # warm
        fes.append(ServingFrontend(eng, max_pending=32))
    router = ReplicaRouter(fes, probe_interval=0.02)
    kill_at = arrivals[int(N * 0.6)]

    async def drive():
        async def fire(i, t0):
            delay = arrivals[i] - (_time.perf_counter() - t0)
            if delay > 0:
                await asyncio.sleep(delay)
            return await router.submit(prompts[i],
                                       max_new_tokens=T_new,
                                       tenant=f"t{i % 3}")

        async def crash(t0):
            await asyncio.sleep(max(0.0, kill_at
                                    - (_time.perf_counter() - t0)))
            victim = max(range(2), key=router.queue_depth)

            def boom():
                raise RuntimeError("bench-injected replica crash")
            fes[victim].engine.step = boom

        async with router:
            t0 = _time.perf_counter()
            outs, _ = await asyncio.gather(
                asyncio.gather(*[fire(i, t0) for i in range(N)]),
                crash(t0))
            wall = _time.perf_counter() - t0
        return outs, wall

    outs, wall = asyncio.run(drive())
    served = sum(len(o) for o in outs)
    stats = router.stats()
    hit_ratio = stats["affinity_hits"] / max(1, stats["dispatches"])
    return {
        "metric": "serving_router",
        "value": round(served / wall, 1), "unit": "tokens/sec",
        "replicas": 2, "requests": N,
        "served_tokens": int(served),
        "affinity_hit_ratio": round(float(hit_ratio), 3),
        "failovers": int(stats["failovers"]),
        "replicas_up_after": len(stats["health"]["up"]),
    }


def bench_serving_prefix_cache():
    """Radix prefix-cache extra (ISSUE 5 acceptance): N requests with a
    shared system-prompt head, cache-on vs cache-off on the SAME
    engine config (tiny GPT, CPU-safe). Reports the hit-token ratio,
    prefilled tokens both sides, and throughput vs cache-off; outputs
    are asserted token-identical so the speedup can't hide a
    correctness break. One compile per engine, both outside the timed
    window."""
    import time as _time

    from paddle_tpu.models.gpt import GPTForGeneration
    from paddle_tpu.serving.engine import ServingEngine

    rng = np.random.RandomState(0)
    # prefill-heavy mix (96-token shared head, 8 new tokens): the
    # regime where prefix reuse pays — a decode-bound mix hides it
    V, T_new, N = 1024, 8, 16
    m = GPTForGeneration(vocab_size=V, hidden_size=128, num_layers=2,
                         num_attention_heads=4,
                         max_position_embeddings=512,
                         compute_dtype="float32")
    m.eval()
    common = rng.randint(1, V, 96).tolist()      # shared system prompt
    prompts = [common + rng.randint(1, V, 8).tolist() for _ in range(N)]
    warm = rng.randint(1, V, 8).tolist()         # disjoint warm prompt

    def run(prefix_caching):
        eng = ServingEngine(m, max_slots=4, block_size=16,
                            max_seq_len=128, cache_dtype="float32",
                            seed=0, prefix_caching=prefix_caching)
        eng.generate_batch([warm], max_new_tokens=2)   # compile
        if eng.prefix_cache is not None:
            eng.prefix_cache.evict_all()
            eng.prefix_cache.hit_tokens = 0
            eng.prefix_cache.miss_tokens = 0
        t0 = _time.perf_counter()
        outs = eng.generate_batch(prompts, max_new_tokens=T_new)
        dt = _time.perf_counter() - t0
        return eng, outs, sum(len(o) for o in outs) / dt

    eng_off, outs_off, tput_off = run(False)
    eng_on, outs_on, tput_on = run(True)
    pc = eng_on.prefix_cache
    extra = {
        "metric": "serving_prefix_cache",
        "value": round(tput_on, 1), "unit": "tokens/sec",
        "cache_off_tokens_per_sec": round(tput_off, 1),
        "speedup_vs_cache_off": round(tput_on / tput_off, 3),
        "hit_token_ratio": round(pc.hit_ratio(), 3),
        "prefilled_tokens_on": int(pc.miss_tokens),
        "prefilled_tokens_off": sum(len(p) for p in prompts),
        "cow_copies": int(pc.cow_copies),
        "outputs_identical": outs_on == outs_off,
        "requests": N,
    }
    if not extra["outputs_identical"]:
        extra["error"] = "prefix-cached outputs diverged from cache-off"
    return extra


def bench_serving_multi_lora():
    """ISSUE 14 extra: K tenants' finetunes through ONE multi-LoRA
    engine vs K per-tenant engines at EQUAL total HBM (the KV block
    budget is split K ways for the solo fleet; adapter slots are the
    multi engine's only extra bytes). Same Poisson-ish interleaved
    stream both sides, outputs asserted token-identical per tenant.
    Reports aggregate tokens/sec both ways, the adapter cache hit
    ratio, and the measured marginal HBM per tenant against the
    analytic 2*r*d*layers-per-projection bound."""
    import time as _time

    from paddle_tpu.models.gpt import GPTForGeneration
    from paddle_tpu.serving.adapters import make_random_adapter
    from paddle_tpu.serving.engine import ServingEngine

    rng = np.random.RandomState(0)
    V, K = 1024, 4
    tenants = [f"tenant{i}" for i in range(K)]
    m = GPTForGeneration(vocab_size=V, hidden_size=128, num_layers=2,
                         num_attention_heads=4,
                         max_position_embeddings=512,
                         compute_dtype="float32")
    m.eval()
    adapters = {t: make_random_adapter(m.decoder, 8, seed=i + 1,
                                       scale=0.1)
                for i, t in enumerate(tenants)}
    n_req = 24
    req_tenants = [tenants[i % K] for i in range(n_req)]
    prompts = [rng.randint(1, V, int(n)).tolist()
               for n in rng.randint(8, 48, n_req)]
    total_blocks = 96                    # the shared HBM budget
    warm = rng.randint(1, V, 8).tolist()

    def multi():
        eng = ServingEngine(m, max_slots=8, block_size=16,
                            max_seq_len=128, cache_dtype="float32",
                            num_blocks=total_blocks + 1, seed=0,
                            max_adapters=K + 1, lora_rank=8)
        for t in tenants:
            eng.register_adapter(t, adapters[t])
        eng.generate_batch([warm], max_new_tokens=2)     # compile
        t0 = _time.perf_counter()
        reqs = [eng.submit(p, 16, adapter_id=t)
                for p, t in zip(prompts, req_tenants)]
        eng.run()
        dt = _time.perf_counter() - t0
        outs = [list(r.output) for r in reqs]
        return eng, outs, sum(len(o) for o in outs) / dt

    def solo_fleet():
        engs = {}
        for t in tenants:
            e = ServingEngine(m, max_slots=2, block_size=16,
                              max_seq_len=128, cache_dtype="float32",
                              num_blocks=total_blocks // K + 1, seed=0,
                              max_adapters=2, lora_rank=8)
            e.register_adapter(t, adapters[t])
            e.generate_batch([warm], max_new_tokens=2)   # compile
            engs[t] = e
        t0 = _time.perf_counter()
        reqs = [engs[t].submit(p, 16, adapter_id=t)
                for p, t in zip(prompts, req_tenants)]
        # round-robin the engines the way one process would
        while any(e.scheduler.has_work for e in engs.values()):
            for e in engs.values():
                if e.scheduler.has_work:
                    e.step()
        dt = _time.perf_counter() - t0
        outs = [list(r.output) for r in reqs]
        return sum(len(o) for o in outs) / dt, outs

    eng, outs_multi, tput_multi = multi()
    tput_solo, outs_solo = solo_fleet()
    bound = sum(2 * eng.adapters.rank
                * max(di, do) * eng.adapters.num_layers * 4
                for _, di, do in eng.adapters.hooks)
    extra = {
        "metric": "serving_multi_lora",
        "value": round(tput_multi, 1), "unit": "tokens/sec",
        "solo_fleet_tokens_per_sec": round(tput_solo, 1),
        "speedup_vs_solo_fleet": round(tput_multi / tput_solo, 3),
        "tenants": K, "requests": n_req,
        "adapter_hit_ratio": round(eng.adapters.hit_ratio(), 3),
        "adapter_evictions": int(eng.adapters.evictions),
        "marginal_bytes_per_tenant": int(eng.adapters.bytes_per_slot),
        "marginal_bytes_bound": int(bound),
        "within_analytic_bound":
            eng.adapters.bytes_per_slot <= bound,
        "outputs_identical": outs_multi == outs_solo,
    }
    if not extra["outputs_identical"]:
        extra["error"] = "multi-LoRA outputs diverged from the " \
            "per-tenant solo fleet"
    if not extra["within_analytic_bound"]:
        extra["error"] = "marginal HBM per tenant exceeds the " \
            "analytic bound"
    return extra


def bench_serving_kv_int8():
    """ISSUE 9 extra: fp32 vs int8 KV block pools on the SAME Poisson
    request stream at an EQUAL HBM budget (tiny GPT, every platform).
    Reports tokens/sec both sides, the max concurrent residents each
    pool held before its first preemption, KV bytes/token (the
    `paddle_tpu_serving_kv_bytes_per_token` gauge value) and the
    greedy token-agreement ratio — so the capacity win can't hide a
    divergence break."""
    import time as _time

    from paddle_tpu.models.gpt import GPTForGeneration
    from paddle_tpu.serving.engine import ServingEngine

    rng = np.random.RandomState(0)
    V, T_new, N = 1024, 12, 24
    m = GPTForGeneration(vocab_size=V, hidden_size=128, num_layers=2,
                         num_attention_heads=4,
                         max_position_embeddings=512,
                         compute_dtype="float32")
    m.eval()
    prompts = [rng.randint(1, V, int(n)).astype(np.int32)
               for n in rng.randint(8, 56, N)]
    arrivals = np.cumsum(rng.exponential(0.002, N))
    arrivals -= arrivals[0]
    warm = rng.randint(1, V, 8).astype(np.int32)

    # equal HBM budget: what 24 fp32 blocks cost, both pools must fit
    # in — tight enough that the fp32 side preempts under the stream.
    # block_bytes is a pure function of the cache geometry, so size
    # the pools from throwaway minimal PagedKVCaches instead of full
    # engine constructions.
    from paddle_tpu.serving.kv_cache import PagedKVCache

    def _block_bytes(kv_dtype):
        return PagedKVCache(
            2, 4, 32, num_blocks=2, block_size=16, max_slots=1,
            max_blocks_per_slot=1, dtype="float32",
            kv_dtype=kv_dtype).block_bytes

    budget = 24 * _block_bytes(None)

    def run(kv_dtype):
        nb = int(budget // _block_bytes(kv_dtype))
        eng = ServingEngine(m, max_slots=8, block_size=16,
                            num_blocks=nb + 1, max_seq_len=128,
                            cache_dtype="float32", kv_dtype=kv_dtype,
                            seed=0)
        eng.generate_batch([warm], max_new_tokens=2)      # compile
        t0 = _time.perf_counter()
        pending = list(zip(prompts, arrivals))
        reqs = []
        residents_pre = 0
        while pending or eng.scheduler.has_work:
            now = _time.perf_counter() - t0
            while pending and pending[0][1] <= now:
                p, _ = pending.pop(0)
                reqs.append(eng.submit(p, T_new))
            if eng.scheduler.preemption_count == 0:
                residents_pre = max(residents_pre,
                                    eng.scheduler.num_active)
            if not eng.step() and pending:
                _time.sleep(max(0.0, pending[0][1]
                                 - (_time.perf_counter() - t0)))
        wall = _time.perf_counter() - t0
        served = sum(len(r.output) for r in reqs)
        return {
            "blocks": nb,
            "tokens_per_sec": round(served / wall, 1),
            "kv_bytes_per_token": int(eng.kv.kv_bytes_per_token),
            "max_residents_before_preemption": int(residents_pre),
            "preemptions": int(eng.scheduler.preemption_count),
            "outputs": [list(r.output) for r in reqs],
        }

    fp = run(None)
    q8 = run("int8")
    total = sum(len(o) for o in fp["outputs"])
    agree = sum(a == b for x, y in zip(fp["outputs"], q8["outputs"])
                for a, b in zip(x, y))
    # cascade-aware: positionwise agreement punishes every token after
    # a single flip (the context legitimately diverged); the prefix
    # metric counts tokens up to each request's first mismatch
    prefix = 0
    for x, y in zip(fp["outputs"], q8["outputs"]):
        for a, b in zip(x, y):
            if a != b:
                break
            prefix += 1
    for r in (fp, q8):
        del r["outputs"]
    return {
        "metric": "serving_kv_int8",
        "value": q8["tokens_per_sec"], "unit": "tokens/sec",
        "fp32": fp, "int8": q8,
        "hbm_budget_bytes": int(budget),
        "capacity_ratio": round(q8["blocks"] / fp["blocks"], 3),
        "greedy_agreement": round(agree / max(1, total), 4),
        "greedy_prefix_agreement": round(prefix / max(1, total), 4),
        "requests": N,
    }


def bench_serving_long_context():
    """ISSUE 15 extra: long-context decode on 8k-token prompts —
    dense vs block-sparse (`sparse_blocks=`), plus an fp8-pool lane,
    on the PR 13 disaggregated topology: each lane prefills on a
    prefill-role engine (big token budget; `track_summaries` keeps
    sparse lanes' prefill at dense speed) and migrates the request
    onto a decode-role engine (decode-sized budget), because the
    small decode budget is where sparsity's read savings show — a
    mixed budget pays full-table gathers for its prefill lanes either
    way. Decode timing starts AFTER 5 warmup steps (the decode
    engine's compile must not ride the timed window — it swamped the
    measurement by 4x during lane bring-up). Reports decode
    tokens/sec, greedy agreement vs the dense lane, the measured
    block-skip ratio, and the fp8 equal-HBM capacity ratio; the fp8
    sub-lane runs at 2k context (its contract is bytes/agreement, not
    the 8k gather roofline — three full 8k prefills would blow the
    suite budget on the CPU container).

    The model is the longctx smoke's needle construction
    (channel-sparse embeddings + identity q/k, hidden 128):
    random-init attention is diffuse — no block selection can serve
    it — while the needle model attends like a trained one, and the
    128-wide head puts CPU decode in the KV-gather-bound regime the
    sparse path targets (at hidden 32 the per-step selection overhead
    outweighs the tiny gathers and sparse measures SLOWER — the same
    inversion a real model sees between short and long context)."""
    import importlib.util
    import os
    import time as _time

    from paddle_tpu.serving.engine import ServingEngine
    from paddle_tpu.serving.kv_cache import PagedKVCache

    spec = importlib.util.spec_from_file_location(
        "longctx_smoke", os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "tools", "longctx_smoke.py"))
    smoke = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(smoke)

    CTX, NEW, BS = 8192, 48, 16
    model = smoke.needle_model(hidden=128, maxpos=CTX + 256)
    rng = np.random.RandomState(0)

    def lane(ctx, sparse=None, **kw):
        geo = dict(max_slots=2, block_size=BS, max_seq_len=ctx + 128,
                   cache_dtype="float32", seed=0)
        pkw = dict(track_summaries=True) if sparse else {}
        skw = dict(sparse_blocks=sparse, sparse_recent=4) \
            if sparse else {}
        pre = ServingEngine(model, role="prefill", token_budget=1024,
                            **geo, **pkw, **kw)
        dec = ServingEngine(model, role="decode", **geo, **skw, **kw)
        prompts = [rng.randint(2, 64, ctx).tolist()]
        reqs = [pre.submit(p, NEW) for p in prompts]
        while any(r.state != "handoff" for r in reqs):
            pre.step()
        dreqs = [dec.submit_migrated(pre.extract_request(r))
                 for r in reqs]
        for _ in range(5):
            dec.step()
        already = sum(len(r.output) for r in dreqs)
        t0 = _time.perf_counter()
        dec.run()
        wall = _time.perf_counter() - t0
        served = sum(len(r.output) for r in dreqs) - already
        return {
            "decode_tokens_per_sec": round(served / wall, 1),
            "kv_bytes_per_token": int(dec.kv.kv_bytes_per_token),
            "skip_ratio": round(dec.sparse_skip_ratio(), 4),
            "outputs": [list(r.output) for r in dreqs],
        }

    rng = np.random.RandomState(0)
    dense = lane(CTX)
    rng = np.random.RandomState(0)          # same prompt per lane
    sparse = lane(CTX, sparse=24)
    rng = np.random.RandomState(0)
    fp32_2k = lane(2048, sparse=24)
    rng = np.random.RandomState(0)
    fp8_2k = lane(2048, sparse=24, kv_dtype="fp8_e4m3")

    def agreement(a, b):
        tot = sum(len(o) for o in a["outputs"])
        return round(sum(x == y for p, q in zip(a["outputs"],
                                                b["outputs"])
                         for x, y in zip(p, q)) / max(1, tot), 4)

    ag_sparse = agreement(dense, sparse)
    ag_fp8 = agreement(fp32_2k, fp8_2k)
    for r in (dense, sparse, fp32_2k, fp8_2k):
        del r["outputs"]

    def _block_bytes(kv_dtype):
        return PagedKVCache(
            2, 1, 32, num_blocks=2, block_size=BS, max_slots=1,
            max_blocks_per_slot=1, dtype="float32",
            kv_dtype=kv_dtype).block_bytes

    return {
        "metric": "serving_long_context",
        "value": sparse["decode_tokens_per_sec"],
        "unit": "decode tokens/sec",
        "context_len": CTX,
        "dense": dense, "sparse": sparse,
        "sparse_fp32_2k": fp32_2k, "sparse_fp8_2k": fp8_2k,
        "speedup_sparse_vs_dense": round(
            sparse["decode_tokens_per_sec"]
            / max(1e-9, dense["decode_tokens_per_sec"]), 2),
        "greedy_agreement_sparse": ag_sparse,
        "greedy_agreement_fp8_vs_fp32_sparse": ag_fp8,
        "fp8_equal_hbm_capacity_ratio": round(
            _block_bytes(None) / _block_bytes("fp8_e4m3"), 2),
    }


def bench_serving_fleet_ops():
    """Fleet control plane extra (ISSUE 17, every platform): cold-start
    seconds from ONE exported bundle for jit-boot vs AOT-boot vs
    AOT+warm-prefix (engine construction through the first
    shared-prefix batch), aggregate tokens/sec through a live
    rolling-upgrade window on a 2-replica fleet (every mid-stream
    request must land on exactly the old or the new checkpoint, never
    a token mix), and autoscaler reaction: simulated burn-to-decision
    seconds (the sustain_s hysteresis floor) plus the real wall
    seconds the applied AOT scale-up boot costs."""
    import asyncio
    import os
    import shutil
    import tempfile
    import time as _time

    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTForGeneration
    from paddle_tpu.serving.distributed import ReplicaRouter
    from paddle_tpu.serving.engine import ServingEngine
    from paddle_tpu.serving.fleet import (AutoscalerPolicy, FleetBundle,
                                          FleetController, SLOAutoscaler,
                                          boot_engine_from_bundle,
                                          export_bundle,
                                          weights_from_model)
    from paddle_tpu.serving.frontend import ServingFrontend
    from paddle_tpu.serving.slo import SLOMonitor

    rng = np.random.RandomState(3)
    V, T_new, N = 193, 8, 12
    kw = dict(max_slots=4, block_size=4, num_blocks=64, max_seq_len=64,
              token_budget=64, cache_dtype="float32", seed=0,
              prefix_caching=True)

    def _model(seed):
        paddle.seed(seed)
        m = GPTForGeneration(vocab_size=V, hidden_size=32, num_layers=2,
                             num_attention_heads=4,
                             max_position_embeddings=128,
                             compute_dtype="float32")
        m.eval()
        return m

    head = rng.randint(1, V, 12).tolist()
    prompts = [head + rng.randint(1, V, int(n)).tolist()
               for n in rng.randint(3, 9, N)]

    tmp = tempfile.mkdtemp(prefix="paddle_tpu_bench_fleet_")
    try:
        exporter = ServingEngine(_model(1234), name="exporter", **kw)
        exporter.generate_batch(prompts[:4], max_new_tokens=T_new)
        bundle = FleetBundle(export_bundle(exporter, tmp, version="v1"))
        spill = os.path.join(tmp, "prefix.pkl")
        exporter.close(spill_prefix=spill)

        def _boot(kind):
            t0 = _time.perf_counter()
            if kind == "jit":
                eng = boot_engine_from_bundle(bundle, aot=False,
                                              name="b_jit")
            elif kind == "aot":
                eng = boot_engine_from_bundle(bundle, name="b_aot")
            else:
                eng = boot_engine_from_bundle(bundle, warm_prefix=spill,
                                              name="b_warm")
            eng.generate_batch(prompts[:2], max_new_tokens=1)
            return eng, _time.perf_counter() - t0

        jit_eng, jit_s = _boot("jit")
        aot_eng, aot_s = _boot("aot")
        warm_eng, warm_s = _boot("warm")
        aot_eng.close()
        warm_eng.close()

        # v1/v2 greedy references from the already-booted jit engine:
        # a mid-upgrade request is valid iff its tokens match exactly
        # one of the two (version purity, never a mix)
        w2 = weights_from_model(_model(777))
        ref1 = jit_eng.generate_batch(prompts, max_new_tokens=T_new)
        jit_eng.swap_weights(w2, "v2")
        ref2 = jit_eng.generate_batch(prompts, max_new_tokens=T_new)
        jit_eng.close()

        fes = [ServingFrontend(
            boot_engine_from_bundle(bundle, name=f"fleet{i}"),
            max_pending=32) for i in range(2)]
        router = ReplicaRouter(fes, probe_interval=0.02)
        ctl = FleetController(router, bundle, spill_dir=tmp)

        clk = [1000.0]
        monitor = SLOMonitor({"default": {"ttft_p95": 0.1},
                              "window_s": 30.0}, clock=lambda: clk[0])
        scaler = SLOAutoscaler(
            ctl, monitor, clock=lambda: clk[0],
            policy=AutoscalerPolicy(min_replicas=2, max_replicas=3,
                                    sustain_s=1.0, recovery_s=2.0,
                                    cooldown_s=3.0))

        async def drive():
            async with router:
                t0 = _time.perf_counter()
                tasks = [asyncio.create_task(
                    router.submit(list(p), max_new_tokens=T_new))
                    for p in prompts]
                await asyncio.sleep(0.01)
                flipped = await ctl.rolling_upgrade(w2, "v2")
                outs = await asyncio.gather(*tasks)
                wall = _time.perf_counter() - t0

                # engineered burn: advance the fake clock until the
                # sustained-burn decision fires, then time the real
                # AOT boot the applied scale-up performs
                burn_t0 = clk[0]
                d = None
                while d is None and clk[0] - burn_t0 < 10.0:
                    monitor.on_ttft("t", 5.0, clk[0])
                    s0 = _time.perf_counter()
                    d = await scaler.step()
                    boot_wall = _time.perf_counter() - s0
                    clk[0] += 0.25
                return flipped, outs, wall, d, burn_t0, boot_wall

        flipped, outs, wall, d, burn_t0, boot_wall = asyncio.run(drive())
        served = sum(len(o) for o in outs)
        n_v2 = sum(o == r2 for o, r2 in zip(outs, ref2))
        pure = all(o in (r1, r2)
                   for o, r1, r2 in zip(outs, ref1, ref2))
        return {
            "metric": "serving_fleet_ops",
            "value": round(served / wall, 1), "unit": "tokens/sec",
            "cold_start_seconds": {
                "jit_boot": round(jit_s, 2),
                "aot_boot": round(aot_s, 2),
                "aot_warm_prefix": round(warm_s, 2),
            },
            "aot_boot_speedup": round(jit_s / max(aot_s, 1e-9), 2),
            "upgrade": {
                "replicas": 2, "requests": N,
                "served_tokens": int(served),
                "flipped": list(flipped),
                "on_new_version": int(n_v2),
                "version_pure_outputs": bool(pure),
            },
            "autoscaler": {
                "reaction_seconds_simulated": round(
                    (d["ts"] - burn_t0) if d else -1.0, 2),
                "sustain_s": 1.0,
                "scale_up_boot_wall_seconds": round(boot_wall, 2),
                "replicas_after": len(ctl.active_replicas()),
            },
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def bench_gpt_moe(on_tpu):
    """ISSUE 10 extra: the MoE GPT lane — hybrid-trainer tokens/sec
    (top-k capacity router, fixed [E, C, d] dispatch einsums) and MoE
    serving tokens/sec through the one-compile mixed step, with the
    expert-utilization entropy / dropped-token / aux-loss record in
    the JSON. min-of-k timed windows per the PR 7 convention. On TPU
    the train config is MoE-350M-class: the 350M dense config with
    its FFN swapped for 8 experts top-2 (~350M active params per
    token, ~1.1B resident); CPU runs a tiny smoke of the same shape."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.parallel.hybrid_gpt import GPTConfig, HybridGPT
    from paddle_tpu.profiler import metrics as _pm

    dev = jax.devices()[0]
    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, seq_len=1024, d_model=1024,
                        n_heads=16, n_layers=24, moe_num_experts=8,
                        moe_top_k=2, moe_capacity_factor=1.25,
                        remat=True, fused_ce=True, ce_seq_chunks=4,
                        bf16_grads=True, compute_dtype=jnp.bfloat16)
        batch, iters, windows = 16, 8, 3
    else:
        cfg = GPTConfig(vocab_size=1024, seq_len=128, d_model=128,
                        n_heads=4, n_layers=2, moe_num_experts=4,
                        moe_top_k=2, moe_capacity_factor=1.25,
                        remat=False, compute_dtype=jnp.float32)
        batch, iters, windows = 4, 3, 2

    trainer = HybridGPT(cfg, devices=[dev])
    params, opt = trainer.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    tok = jnp.asarray(rng.randint(0, cfg.vocab_size,
                                  (batch, cfg.seq_len)), jnp.int32)
    lab = jnp.asarray(rng.randint(0, cfg.vocab_size,
                                  (batch, cfg.seq_len)), jnp.int32)
    for w in range(3):
        params, opt, loss = trainer.train_step(params, opt, tok, lab,
                                               step_num=w + 1)
        float(jax.device_get(loss))
    step_num, best = 4, float("inf")
    for k in range(windows):
        t0 = time.perf_counter()
        for _ in range(iters):
            params, opt, loss = trainer.train_step(
                params, opt, tok, lab, step_num=step_num)
            step_num += 1
        final_loss = float(jax.device_get(loss))
        best = min(best, time.perf_counter() - t0)
        if k and _budget_left() < 120:
            break
    assert np.isfinite(final_loss)
    train_tps = batch * cfg.seq_len * iters / best
    trainer.flush_moe_metrics()      # drain the one-step metric lag
    tstats = jax.device_get(trainer.last_moe_stats)
    # MFU against ACTIVE params (top_k experts per token), the MoE
    # convention — total params would flatter a sparse model. ONE
    # formula: auto_tuner.ModelSpec.active_params/useful_flops is the
    # same accounting the placement search predicts MFU with
    from paddle_tpu.parallel.auto_tuner import ModelSpec
    mspec = ModelSpec(
        n_layers=cfg.n_layers, d_model=cfg.d_model,
        seq_len=cfg.seq_len, vocab_size=cfg.vocab_size, d_ff=cfg.d_ff,
        global_batch=batch, moe_experts=cfg.moe_experts,
        moe_top_k=cfg.moe_top_k,
        moe_capacity_factor=cfg.moe_capacity_factor)
    flops_tok = mspec.useful_flops() / (batch * cfg.seq_len)
    train_mfu = train_tps * flops_tok / PEAK_FLOPS

    # serving phase: tiny MoE engine on every platform (the serving
    # extras discipline), greedy stream through the ONE mixed step
    from paddle_tpu.models.gpt import GPTForGeneration
    from paddle_tpu.serving.engine import ServingEngine
    m = GPTForGeneration(vocab_size=1024, hidden_size=128,
                         num_layers=2, num_attention_heads=4,
                         max_position_embeddings=512,
                         compute_dtype="float32",
                         moe=dict(num_expert=4, top_k=2,
                                  capacity_factor=2.0))
    m.eval()
    prompts = [rng.randint(1, 1024, int(n)).astype(np.int32)
               for n in rng.randint(8, 56, 16)]
    eng = ServingEngine(m, max_slots=8, block_size=16,
                        max_seq_len=128, cache_dtype="float32", seed=0)
    eng.generate_batch([prompts[0]], max_new_tokens=2)    # compile
    t0 = time.perf_counter()
    outs = eng.generate_batch(prompts, max_new_tokens=12)
    serve_wall = time.perf_counter() - t0
    served = sum(len(o) for o in outs)
    if _pm._enabled:
        _pm.TOKENS_PER_SEC.set(train_tps)
    return {
        "metric": "gpt_moe",
        "value": round(train_tps, 1), "unit": "tokens/sec",
        "train_tokens_per_sec": round(train_tps, 1),
        "train_mfu_active": round(train_mfu, 4) if on_tpu else None,
        "train_loss": round(final_loss, 4),
        "train_aux_loss": round(float(tstats["balance"]), 4),
        "train_dropped_tokens": int(tstats["dropped"]),
        "serving_tokens_per_sec": round(served / serve_wall, 1),
        "moe_expert_utilization": round(
            eng.moe_utilization_entropy(), 4),
        "moe_dropped_tokens_total": int(eng.moe_dropped_total),
        "moe_aux_loss": round(eng.moe_last_aux, 4),
        "experts": cfg.moe_experts, "top_k": cfg.moe_top_k,
        "capacity_factor": cfg.moe_capacity_factor,
    }


def bench_kernel_autotune(on_tpu):
    """ISSUE 11 extra: the measurement-driven Pallas kernel autotuner.

    Three records, every platform:
      * paged decode tuned-vs-default tok/s — the engine-level KV
        block-size search (`tune_block_size`, parity-gated candidates
        sharing the dispatch gate's alignment predicate) and the same
        decode-heavy stream served at the default 16 vs the winner;
      * MoE dispatch einsum-vs-indexed tok/s — the one-hot [T,k,C]
        dispatch/combine einsums against the index-table gather pair
        the grouped-expert kernel rides (backend-independent; on TPU
        the grouped Pallas matmul adds MXU tiling on top —
        docs/KERNELS.md carries the expected-effect analysis);
      * cache contract — search seconds, cache-hit ratio for this
        process, and the hit-is-zero-cost assertion (1k lookups, no
        searcher invocation).
    Searches persist into a throwaway cache so a bench run never
    mutates the operator's tuned cache."""
    import os
    import tempfile

    import jax
    import jax.numpy as jnp

    from paddle_tpu.models.gpt import GPTForGeneration
    from paddle_tpu.ops.pallas import autotune
    from paddle_tpu.ops.pallas import paged_attention as pa_mod
    from paddle_tpu.parallel import moe_utils
    from paddle_tpu.serving.engine import ServingEngine

    tmp = tempfile.NamedTemporaryFile(suffix=".json", delete=False)
    tmp.close()
    old_cache = os.environ.get("PADDLE_TPU_KERNEL_CACHE")
    os.environ["PADDLE_TPU_KERNEL_CACHE"] = tmp.name
    autotune.reset_for_tests()
    try:
        import paddle_tpu as paddle
        paddle.seed(1234)
        m = GPTForGeneration(vocab_size=512, hidden_size=64,
                             num_layers=2, num_attention_heads=4,
                             max_position_embeddings=256,
                             compute_dtype="float32")
        m.eval()
        H, Dh = 4, 16
        rng = np.random.RandomState(0)
        prompts = [rng.randint(1, 512, int(n)).astype(np.int32)
                   for n in rng.randint(16, 48, 16)]

        def serve_tok_s(block_size):
            eng = ServingEngine(m, max_slots=8, block_size=block_size,
                                max_seq_len=128,
                                cache_dtype="float32", seed=0)
            eng.generate_batch([prompts[0]], max_new_tokens=2)
            t0 = time.perf_counter()
            outs = eng.generate_batch(prompts, max_new_tokens=24)
            dt = time.perf_counter() - t0
            return sum(len(o) for o in outs) / dt, outs

        default_tok_s, ref_outs = serve_tok_s(16)
        res = pa_mod.tune_block_size(8, H, Dh, context_len=64,
                                     budget_s=20.0)
        tuned_bs = int(res.config["block_size"])
        tuned_tok_s, tuned_outs = serve_tok_s(tuned_bs)
        assert tuned_outs == ref_outs    # block size never changes tokens

        # MoE dispatch representation: one-hot einsums vs index tables
        T, E, k, d = 256, 8, 2, 128
        C = moe_utils.expert_capacity(T, E, k, 1.25)
        logits = jnp.asarray(rng.randn(T, E).astype(np.float32))
        x = jnp.asarray(rng.randn(T, d).astype(np.float32))
        r = moe_utils.top_k_routing(logits, k, C)

        @jax.jit
        def einsum_pair(x):
            disp = moe_utils.dispatch_tokens(x, r.plan)
            return moe_utils.combine_tokens(disp, r.plan)

        @jax.jit
        def indexed_pair(x):
            disp = moe_utils.dispatch_tokens_indexed(x, r.plan, E, C)
            return moe_utils.combine_tokens_indexed(disp, r.plan)

        def rate(fn):
            fn(x).block_until_ready()
            iters = 30
            t0 = time.perf_counter()
            for _ in range(iters):
                out = fn(x)
            out.block_until_ready()
            return T * iters / (time.perf_counter() - t0)

        einsum_tok_s = rate(einsum_pair)
        indexed_tok_s = rate(indexed_pair)

        # cache contract: the tuned winner is a hit, hits cost nothing
        bucket = autotune.shape_bucket(8, H, Dh)
        t0 = time.perf_counter()
        for _ in range(1000):
            cfg = autotune.ensure(
                "paged_block_size", bucket, np.float32, default=None,
                searcher=lambda: (_ for _ in ()).throw(
                    AssertionError("searched on a cache hit")))
        lookup_ms = (time.perf_counter() - t0) * 1e3
        assert cfg == res.config
        req = autotune.requested()
        hit_ratio = (sum(req.values()) / len(req)) if req else 0.0

        return {
            "metric": "kernel_autotune",
            "value": round(tuned_tok_s, 1), "unit": "tokens/sec",
            "paged_decode": {
                "default_block_size": 16,
                "tuned_block_size": tuned_bs,
                "default_tokens_per_sec": round(default_tok_s, 1),
                "tuned_tokens_per_sec": round(tuned_tok_s, 1),
                "outputs_identical": True,
                "search_seconds": round(res.elapsed, 2),
                "candidates_tried": res.tried,
                "candidates_rejected_parity": res.rejected,
            },
            "moe_dispatch": {
                "einsum_tokens_per_sec": round(einsum_tok_s, 1),
                "indexed_tokens_per_sec": round(indexed_tok_s, 1),
                "speedup": round(indexed_tok_s / einsum_tok_s, 2),
                "shape": {"T": T, "E": E, "top_k": k, "d": d, "C": C},
            },
            "cache_hit_ratio": round(hit_ratio, 3),
            "cache_lookup_ms_per_1k": round(lookup_ms, 2),
            "cache_hit_zero_cost": lookup_ms < 200.0,
            "backend": autotune.backend_key(),
            "note": (None if on_tpu else
                     "CPU: search machinery + cache contract; the "
                     "Pallas tile wins are TPU-only by design "
                     "(docs/KERNELS.md expected-effect analysis)"),
        }
    finally:
        autotune.reset_for_tests()
        if old_cache is None:
            os.environ.pop("PADDLE_TPU_KERNEL_CACHE", None)
        else:
            os.environ["PADDLE_TPU_KERNEL_CACHE"] = old_cache
        try:
            os.unlink(tmp.name)
        except OSError:
            pass


def _metrics_extra():
    """Condensed observability snapshot for the benchmark JSON `extras`
    (only when PADDLE_TPU_METRICS is set — instrumentation off keeps the
    headline run unperturbed)."""
    from paddle_tpu.profiler import metrics
    if not metrics._enabled:
        return None
    snap = metrics.REGISTRY.snapshot()

    def total(name):
        return round(sum(
            v for v in snap.get(name, {}).get("values", {}).values()
            if isinstance(v, (int, float))), 3)

    return {
        "metric": "observability_snapshot",
        "dispatch_ops": total("paddle_tpu_dispatch_ops_total"),
        "jit_compiles": total("paddle_tpu_jit_compiles_total"),
        "jit_compile_seconds": total(
            "paddle_tpu_jit_compile_seconds_total"),
        "collective_bytes": total("paddle_tpu_collective_bytes_total"),
        "grad_buckets": total("paddle_tpu_grad_buckets"),
        "pipeline_bubble_ticks": total(
            "paddle_tpu_pipeline_stage_bubble_ticks"),
        "pipeline_bubble_ratio": round(
            metrics.PIPELINE_BUBBLE_RATIO.value, 4),
        "tokens_per_sec_gauge": round(metrics.TOKENS_PER_SEC.value, 1),
        "moe_expert_tokens": total("paddle_tpu_moe_expert_tokens_total"),
        "moe_dropped_tokens": total(
            "paddle_tpu_moe_dropped_tokens_total"),
        "moe_expert_utilization": round(
            metrics.MOE_EXPERT_UTILIZATION.labels("serving").value, 4),
        # expert-weight HBM per dtype for the gpt_moe bench shape
        # (ISSUE 14): what the weight-only knob buys at serving time —
        # analytic, scales included (grouped_matmul.expert_weight_bytes)
        "moe_expert_weight_bytes": _expert_weight_bytes_by_dtype(),
        # request tracing + SLO plane (ISSUE 16): nonzero when the run
        # also sets PADDLE_TPU_TRACE=1 (tracing, like the rest of the
        # instrumentation, stays off unless asked for)
        "trace_requests": total("paddle_tpu_serving_trace_requests_total"),
        "trace_events": total("paddle_tpu_serving_trace_events_total"),
        "trace_events_dropped": total(
            "paddle_tpu_serving_trace_events_dropped_total"),
        "trace_open": total("paddle_tpu_serving_trace_active"),
        "slo_breaches": total("paddle_tpu_serving_slo_breaches_total"),
        "flight_steps": _flight_steps(),
    }


def _flight_steps():
    """Per-step flight-recorder coverage across every engine this bench
    process created (serving.tracing.StepFlightRecorder)."""
    from paddle_tpu.serving import tracing
    return int(sum(rec.steps for rec in tracing.flight_recorders()))


def _expert_weight_bytes_by_dtype():
    """bf16 / int8 / int4 expert-stack bytes (both FFN mats + scales)
    for the gpt_moe bench shape — 8 experts on the 350M-class config."""
    from paddle_tpu.ops.pallas.grouped_matmul import expert_weight_bytes
    L, E, D, F = 24, 8, 1024, 4096
    return {dt: int(expert_weight_bytes(E, D, F, dt, L)
                    + expert_weight_bytes(E, F, D, dt, L))
            for dt in ("bfloat16", "int8", "int4")}


def main():
    import os

    import jax
    if os.environ.get("PADDLE_TPU_METRICS"):
        from paddle_tpu.profiler import metrics as _m
        _m.enable()
    dev = jax.devices()[0]
    on_tpu = dev.platform in ("tpu", "axon")

    tps, gpt_mfu, tuner_extra = bench_gpt(on_tpu)
    result = {
        "metric": ("gpt2_350m_train_tokens_per_sec_per_chip" if on_tpu
                   else "gpt_tiny_cpu_smoke_tokens_per_sec"),
        "value": round(tps, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(tps / 10_000.0, 3) if on_tpu else None,
        "mfu": round(gpt_mfu, 4) if on_tpu else None,
        "extras": [],
    }
    # placement-search extra (ISSUE 7): the tuner's predicted MFU rides
    # next to the measured number so the prediction gap is in the record
    result["extras"].append(tuner_extra)

    # layout mode of record for the vision configs (ISSUE 4): which
    # path produced the resnet/lenet numbers in this run
    from paddle_tpu.core import layout as _layout_mod
    result["extras"].append({
        "metric": "layout_mode",
        "value": ("nhwc_propagated" if _layout_mod.enabled()
                  else "nchw_per_op"),
        "s2d_stem": _layout_mod.s2d_stem_enabled(),
    })

    # serving extra runs on every platform (CPU tiny GPT) and carries
    # the continuous-batching >= 2x-vs-sequential driver contract —
    # run it BEFORE the TPU extras so a long compile tail (e.g. the
    # speculative decode extra) can't starve it out of the budget
    try:
        result["extras"].append(bench_serving())
    except Exception as e:  # noqa: BLE001
        result["extras"].append(
            {"metric": "serving_continuous_batching",
             "error": f"{type(e).__name__}: {e}"})

    # prefix-cache extra: same every-platform discipline (tiny GPT,
    # shared-system-prompt stream, hit ratio + throughput vs cache-off)
    try:
        result["extras"].append(bench_serving_prefix_cache())
    except Exception as e:  # noqa: BLE001
        result["extras"].append(
            {"metric": "serving_prefix_cache",
             "error": f"{type(e).__name__}: {e}"})

    # multi-replica router extra: every-platform (2 CPU-capable tiny
    # replicas, Poisson multi-tenant stream, forced mid-stream crash)
    try:
        result["extras"].append(bench_serving_router())
    except Exception as e:  # noqa: BLE001
        result["extras"].append(
            {"metric": "serving_router",
             "error": f"{type(e).__name__}: {e}"})

    # multi-tick decode lane (ISSUE 18): every-platform — decode
    # tok/s and inter-token p50/p99 at ticks_per_dispatch 1/4/8, plus
    # the sync-vs-async host-stall share the overlapped readback buys
    try:
        result["extras"].append(bench_serving_multitick())
    except Exception as e:  # noqa: BLE001
        result["extras"].append(
            {"metric": "serving_multitick",
             "error": f"{type(e).__name__}: {e}"})

    # on-device speculation lane (ISSUE 19): every-platform — draft_k=3
    # inside the N=8 while_loop vs the spec-N1 and nospec-N8 baselines,
    # accept rate on drafter-friendly prompts, token-identity record
    try:
        result["extras"].append(bench_serving_spec_multitick())
    except Exception as e:  # noqa: BLE001
        result["extras"].append(
            {"metric": "serving_spec_multitick",
             "error": f"{type(e).__name__}: {e}"})

    # disaggregated prefill/decode extra: every-platform (1 prefill +
    # 1 decode vs 2 monolithic replicas at equal chip count, mixed
    # long-prompt/short-decode Poisson stream — p99 inter-token is the
    # interference metric the split exists to fix)
    try:
        result["extras"].append(bench_serving_disagg())
    except Exception as e:  # noqa: BLE001
        result["extras"].append(
            {"metric": "serving_disagg",
             "error": f"{type(e).__name__}: {e}"})

    # int8-KV extra: every-platform (fp32 vs int8 pools at equal HBM
    # budget on the same Poisson stream — capacity + agreement record)
    try:
        result["extras"].append(bench_serving_kv_int8())
    except Exception as e:  # noqa: BLE001
        result["extras"].append(
            {"metric": "serving_kv_int8",
             "error": f"{type(e).__name__}: {e}"})

    # multi-LoRA lane (ISSUE 14): K tenants through one engine vs K
    # per-tenant engines at equal HBM — every platform
    try:
        result["extras"].append(bench_serving_multi_lora())
    except Exception as e:  # noqa: BLE001
        result["extras"].append(
            {"metric": "serving_multi_lora",
             "error": f"{type(e).__name__}: {e}"})

    # fleet control plane lane (ISSUE 17): every-platform — jit vs AOT
    # vs AOT+warm-prefix cold-start seconds from one bundle, tokens/sec
    # through a live rolling-upgrade window, autoscaler reaction record
    if _budget_left() > 120:
        try:
            result["extras"].append(bench_serving_fleet_ops())
        except Exception as e:  # noqa: BLE001
            result["extras"].append(
                {"metric": "serving_fleet_ops",
                 "error": f"{type(e).__name__}: {e}"})
    else:
        result["extras"].append(
            {"metric": "serving_fleet_ops", "skipped": "time budget"})

    # long-context lane (ISSUE 15): 8k-token prompts migrated onto
    # decode-role engines — dense vs block-sparse decode tok/s +
    # agreement, fp8 pools at 2k + equal-HBM capacity. The two
    # 8k-token prefills dominate its wall time (~80 s each on the
    # 1-core CPU container), so it needs real budget headroom.
    if _budget_left() > 360:
        try:
            result["extras"].append(bench_serving_long_context())
        except Exception as e:  # noqa: BLE001
            result["extras"].append(
                {"metric": "serving_long_context",
                 "error": f"{type(e).__name__}: {e}"})
    else:
        result["extras"].append(
            {"metric": "serving_long_context",
             "skipped": "insufficient wall-clock budget (needs ~5-6 "
                        "min: two 8k-context prefills + fp8 lanes on "
                        "CPU)"})

    # MoE lane (ISSUE 10): every-platform — hybrid MoE train tok/s
    # (MoE-350M-class on TPU) + MoE serving tok/s + utilization record
    if _budget_left() > 90:
        try:
            result["extras"].append(bench_gpt_moe(on_tpu))
        except Exception as e:  # noqa: BLE001
            result["extras"].append(
                {"metric": "gpt_moe",
                 "error": f"{type(e).__name__}: {e}"})
    else:
        result["extras"].append(
            {"metric": "gpt_moe", "skipped": "time budget"})

    # kernel-autotune extra (ISSUE 11): every-platform — block-size
    # search + tuned-vs-default decode tok/s, MoE dispatch
    # representation A/B, cache-hit-zero-cost contract
    if _budget_left() > 60:
        try:
            result["extras"].append(bench_kernel_autotune(on_tpu))
        except Exception as e:  # noqa: BLE001
            result["extras"].append(
                {"metric": "kernel_autotune",
                 "error": f"{type(e).__name__}: {e}"})
    else:
        result["extras"].append(
            {"metric": "kernel_autotune", "skipped": "time budget"})

    # embedding-engine extra: every-platform (localhost PS servers +
    # CPU dense step) with the >= 1.3x-vs-direct driver contract
    if _budget_left() > 60:
        try:
            result["extras"].append(bench_wide_deep_heter())
        except Exception as e:  # noqa: BLE001
            result["extras"].append(
                {"metric": "wide_deep_heter_examples_per_sec",
                 "error": f"{type(e).__name__}: {e}"})
    else:
        result["extras"].append(
            {"metric": "wide_deep_heter_examples_per_sec",
             "skipped": "time budget"})

    # graph-engine lane (ISSUE 20): every-platform (localhost PS
    # servers + jitted SAGE step) with the pipelined >= 1.2x-vs-direct
    # driver contract
    if _budget_left() > 60:
        try:
            result["extras"].append(bench_graph_sage())
        except Exception as e:  # noqa: BLE001
            result["extras"].append(
                {"metric": "graph_sage_examples_per_sec",
                 "error": f"{type(e).__name__}: {e}"})
    else:
        result["extras"].append(
            {"metric": "graph_sage_examples_per_sec",
             "skipped": "time budget"})

    if on_tpu:
        for name, fn, unit in (
                ("resnet50_train_imgs_per_sec_per_chip", bench_resnet50,
                 "imgs/sec"),
                ("bert_base_lamb_train_seqs_per_sec_per_chip", bench_bert,
                 "seqs/sec"),
                ("lenet_fit_steps_per_sec", bench_lenet, "steps/sec"),
                ("wide_deep_ps_examples_per_sec", bench_wide_deep,
                 "examples/sec"),
                ("gpt2_350m_decode_tokens_per_sec_per_chip", bench_decode,
                 "tokens/sec"),
                ("gpt2_350m_decode_speculative_b1_tokens_per_sec",
                 bench_decode_speculative, "tokens/sec")):
            # drop the previous config's device buffers: trainers hold
            # reference cycles (mesh/jit closures), so HBM is only
            # reclaimed after a cycle collection
            import gc
            gc.collect()
            if _budget_left() < 60:
                result["extras"].append(
                    {"metric": name, "skipped": "time budget"})
                continue
            try:
                res = fn()
            except Exception as e:
                result["extras"].append(
                    {"metric": name, "error": f"{type(e).__name__}: {e}"})
                continue
            # (value, mfu) or (value, mfu, secondary-metric dict)
            val, mfu, extra_metric = (tuple(res) + (None,))[:3]
            if val is None:
                result["extras"].append(
                    {"metric": name, "skipped": "not available"})
                continue
            result["extras"].append({
                "metric": name, "value": round(val, 1), "unit": unit,
                "mfu": round(mfu, 4) if mfu else None})
            if extra_metric is not None:
                result["extras"].append(extra_metric)

    obs = _metrics_extra()
    if obs is not None:
        result["extras"].append(obs)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
